"""Offered-load sweep for mx.serving.InferenceServer.

For each offered QPS, open-loop submitters fire single-item requests at
exponential inter-arrival times for --duration seconds, then one JSON
line per load point reports achieved QPS, latency quantiles, mean batch
occupancy, and the reject/expire rates — the capacity-planning companion
to tools/perf_probe.py (same style: stdlib-only CLI, JSON out).

With ``--router N`` the sweep instead drives a resilient Router front
door over N single-replica InferenceServers with a mixed SLO workload
(interactive + sheddable batch) and hard-kills one backend halfway
through each load point — the row then reports per-SLO-class p50/p99,
achieved throughput, and the failover/shed accounting, so the record
doubles as a "replica death costs latency, not errors" regression check.

Usage:
  python tools/bench_serving.py [--load 50,200,800] [--duration 3]
                                [--max-batch 32] [--max-wait-us 2000]
                                [--hidden 256] [--in-dim 512]
                                [--replicas 1] [--router 0]
                                [--batch-frac 0.2]
                                [--out bench_serving.jsonl]
"""
import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_server(cli):
    import numpy as np
    import mxnet_tpu as mx

    rng = np.random.RandomState(0)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=cli.hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=cli.hidden, name="fc2")
    params = {
        "fc1_weight": mx.nd.array(
            rng.randn(cli.hidden, cli.in_dim).astype(np.float32) * 0.05),
        "fc1_bias": mx.nd.array(np.zeros(cli.hidden, np.float32)),
        "fc2_weight": mx.nd.array(
            rng.randn(cli.hidden, cli.hidden).astype(np.float32) * 0.05),
        "fc2_bias": mx.nd.array(np.zeros(cli.hidden, np.float32)),
    }
    ctx = ([mx.current_context()] if cli.replicas == 1
           else [mx.cpu(i) for i in range(cli.replicas)])
    return mx.serving.InferenceServer(
        net, params, {"data": (cli.max_batch, cli.in_dim)}, ctx=ctx,
        max_wait_us=cli.max_wait_us, max_queue=cli.max_queue)


def run_load_point(srv, offered_qps, duration, in_dim, n_threads=8):
    import numpy as np
    from mxnet_tpu import serving

    x = np.zeros(in_dim, np.float32)
    stop_at = time.monotonic() + duration
    counts = {"submitted": 0, "rejected": 0, "expired": 0}
    lock = threading.Lock()
    futures = []
    per_thread_qps = offered_qps / n_threads

    def submitter(seed):
        rng = random.Random(seed)
        while time.monotonic() < stop_at:
            time.sleep(rng.expovariate(per_thread_qps))
            try:
                fut = srv.submit(data=x)
                with lock:
                    counts["submitted"] += 1
                    futures.append(fut)
            except serving.QueueFullError:
                with lock:
                    counts["rejected"] += 1

    t0 = time.monotonic()
    threads = [threading.Thread(target=submitter, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for fut in futures:
        try:
            fut.result(timeout=60)
        except serving.DeadlineExceededError:
            counts["expired"] += 1
    elapsed = time.monotonic() - t0
    snap = srv.metrics.snapshot()
    occ = snap["occupancy_hist"]
    total_items = sum(n * c for n, c in occ.items())
    return {
        "offered_qps": offered_qps,
        "achieved_qps": counts["submitted"] / elapsed,
        "submitted": counts["submitted"],
        "rejected": counts["rejected"],
        "expired": counts["expired"],
        "latency_ms_p50": snap["latency_ms_p50"],
        "latency_ms_p99": snap["latency_ms_p99"],
        "batches": snap["batches_total"],
        "mean_batch_occupancy": (total_items / snap["batches_total"]
                                 if snap["batches_total"] else 0.0),
        "padded_items": snap["padded_items_total"],
        "queue_depth_peak": snap["queue_depth_peak"],
    }


def build_router_fleet(cli):
    import mxnet_tpu as mx

    n = cli.router
    srvs = [build_server(cli) for _ in range(n)]
    return srvs, mx.serving.Router(srvs, seed=0)


def run_router_point(router, victim, offered_qps, duration, in_dim,
                     batch_frac, n_threads=8):
    """One open-loop load point through the Router with a mixed SLO
    workload; the victim backend is hard-killed (no drain) halfway
    through, so the row captures failover behaviour, not steady state."""
    import numpy as np
    from mxnet_tpu import serving

    x = np.zeros(in_dim, np.float32)
    stop_at = time.monotonic() + duration
    counts = {"submitted": 0, "shed": 0, "failed": 0, "expired": 0}
    lock = threading.Lock()
    futures = []
    per_thread_qps = offered_qps / n_threads

    def submitter(seed):
        rng = random.Random(seed)
        while time.monotonic() < stop_at:
            time.sleep(rng.expovariate(per_thread_qps))
            slo = "batch" if rng.random() < batch_frac else "interactive"
            try:
                fut = router.submit(slo=slo, data=x)
                with lock:
                    counts["submitted"] += 1
                    futures.append(fut)
            except serving.RouterOverloadError:
                with lock:
                    counts["shed"] += 1

    killer = threading.Timer(duration / 2,
                             lambda: victim.stop(drain=False))
    t0 = time.monotonic()
    threads = [threading.Thread(target=submitter, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    killer.start()
    for t in threads:
        t.join()
    killer.join()
    for fut in futures:
        try:
            fut.result(timeout=60)
        except serving.DeadlineExceededError:
            counts["expired"] += 1
        except Exception:
            counts["failed"] += 1
    elapsed = time.monotonic() - t0
    snap = router.metrics.snapshot()
    row = {
        "mode": "router",
        "offered_qps": offered_qps,
        "achieved_qps": counts["submitted"] / elapsed,
        "submitted": counts["submitted"],
        "shed": counts["shed"],
        "failed": counts["failed"],
        "expired": counts["expired"],
        "retries": snap["retries"],
        "hedges": snap["hedges"],
        "breaker_transitions": snap["breaker_transitions"],
    }
    for slo in ("interactive", "batch"):
        for q, key in ((.50, "p50"), (.99, "p99")):
            v = router.metrics.latency_quantile(q, slo)
            if v is not None:
                row["latency_ms_%s_%s" % (key, slo)] = v
    return row


def run_autoscale_phase(cli):
    """Drive a diurnal load curve (the --load points, in order, each for
    --duration seconds) through a registry-backed Router while the
    Autoscaler grows/shrinks the fleet, and emit ONE BENCH record:
    offered curve, scale events, per-class p50/p99, SLO violations, and
    the warm-start cold_bucket_runs of every spawned replica."""
    import tempfile

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import serving

    rng = np.random.RandomState(0)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=cli.hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=cli.hidden, name="fc2")
    params = {
        "fc1_weight": mx.nd.array(
            rng.randn(cli.hidden, cli.in_dim).astype(np.float32) * 0.05),
        "fc1_bias": mx.nd.array(np.zeros(cli.hidden, np.float32)),
        "fc2_weight": mx.nd.array(
            rng.randn(cli.hidden, cli.hidden).astype(np.float32) * 0.05),
        "fc2_bias": mx.nd.array(np.zeros(cli.hidden, np.float32)),
    }
    tmp = tempfile.mkdtemp(prefix="bench-autoscale-")
    prefix = os.path.join(tmp, "m")
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    shapes = {"data": (cli.max_batch, cli.in_dim)}
    server_kw = dict(max_wait_us=cli.max_wait_us, max_queue=cli.max_queue)
    cache_prev = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    os.environ["MXNET_COMPILE_CACHE_DIR"] = os.path.join(tmp, "cache")

    spawned = []

    class Provider(serving.LocalCheckpointProvider):
        def spawn(self):
            t0 = time.monotonic()
            name, server = super().spawn()
            spawned.append((name, server,
                            (time.monotonic() - t0) * 1e3))
            return name, server

    registry = serving.ReplicaRegistry(ttl_ms=3000)
    seed_srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, shapes, attach_aot=False, **server_kw)
    seed_srv.save_aot_bundle(prefix, 1)
    stop_beat = serving.start_heartbeater(registry, "seed0", seed_srv,
                                          interval_ms=500)
    router = serving.Router(registry=registry, registry_sync_ms=100, seed=0)
    provider = Provider(prefix, 1, shapes, registry=registry,
                        attach_aot=True, **server_kw)
    scaler = serving.Autoscaler(
        router, provider, min_replicas=1, max_replicas=cli.autoscale,
        interval_ms=100, hysteresis=2, cooldown_ms=500,
        drain_timeout_ms=10000)
    scaler.start()

    x = np.zeros(cli.in_dim, np.float32)
    lock = threading.Lock()
    counts = {"submitted": 0, "shed": 0, "failed": 0, "expired": 0}
    futures = []
    loads = [float(s) for s in cli.load.split(",") if s]
    curve = []
    peak = [1]
    try:
        for qps in loads:
            stop_at = time.monotonic() + cli.duration
            per_thread = qps / 8

            def submitter(seed):
                prng = random.Random(seed)
                while time.monotonic() < stop_at:
                    time.sleep(prng.expovariate(per_thread))
                    slo = ("batch" if prng.random() < cli.batch_frac
                           else "interactive")
                    try:
                        fut = router.submit(slo=slo, data=x)
                        with lock:
                            counts["submitted"] += 1
                            futures.append(fut)
                    except serving.RouterOverloadError:
                        with lock:
                            counts["shed"] += 1

            t0 = time.monotonic()
            threads = [threading.Thread(target=submitter, args=(i,),
                                        daemon=True) for i in range(8)]
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                sig = router.signals()
                peak[0] = max(peak[0], sig["replicas"] - sig["draining"])
                time.sleep(0.1)
            sig = router.signals()
            curve.append({"offered_qps": qps,
                          "replicas_at_end": sig["replicas"]
                          - sig["draining"],
                          "pressure_at_end": round(sig["pressure"], 3),
                          "elapsed_s": round(time.monotonic() - t0, 2)})
        for fut in futures:
            try:
                fut.result(timeout=60)
            except serving.DeadlineExceededError:
                counts["expired"] += 1
            except Exception:
                counts["failed"] += 1
    finally:
        scaler.stop(retire_owned=True)
        router.close()
        stop_beat()
        seed_srv.stop(drain=True)
        registry.close()
        if cache_prev is None:
            os.environ.pop("MXNET_COMPILE_CACHE_DIR", None)
        else:
            os.environ["MXNET_COMPILE_CACHE_DIR"] = cache_prev

    snap = router.metrics.snapshot()
    row = {
        "metric": "serving_autoscale",
        "mode": "autoscale",
        "value": counts["submitted"],
        "unit": "requests",
        "load_curve": curve,
        "submitted": counts["submitted"],
        "failed": counts["failed"],
        "shed": counts["shed"],
        "expired": counts["expired"],
        "slo_violations_interactive": snap["expired"].get("interactive", 0)
        + snap["shed"].get("interactive", 0),
        "peak_replicas": peak[0],
        "scale_events": [{k: e[k] for k in ("op", "ok", "why")
                          if k in e} for e in scaler.events],
        "spawns": [{"replica": n, "spawn_ms": round(ms, 1),
                    "cold_bucket_runs": s.cold_bucket_runs()}
                   for n, s, ms in spawned],
    }
    for slo in ("interactive", "batch"):
        for q, key in ((.50, "p50"), (.99, "p99")):
            v = router.metrics.latency_quantile(q, slo)
            if v is not None:
                row["latency_ms_%s_%s" % (key, slo)] = v
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--load", default="50,200,800",
                    help="comma-separated offered QPS points")
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-wait-us", type=int, default=2000)
    ap.add_argument("--max-queue", type=int, default=1024)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--in-dim", type=int, default=512)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--router", type=int, default=0, metavar="N",
                    help="route through a Router over N backend servers, "
                         "killing one mid-run (0 = plain server sweep)")
    ap.add_argument("--batch-frac", type=float, default=0.2,
                    help="fraction of router traffic in the sheddable "
                         "'batch' SLO class")
    ap.add_argument("--autoscale", type=int, default=0, metavar="MAX",
                    help="drive the --load points as one diurnal curve "
                         "through a registry-backed Router while the "
                         "Autoscaler scales 1..MAX replicas; emits one "
                         "BENCH record with the scale-event trace")
    ap.add_argument("--out", default=None,
                    help="also append JSON lines to this file")
    cli = ap.parse_args()

    if cli.autoscale:
        row = run_autoscale_phase(cli)
        line = json.dumps(row)
        print(line, flush=True)
        if cli.out:
            with open(cli.out, "a") as sink:
                sink.write(line + "\n")
        return

    loads = [float(s) for s in cli.load.split(",") if s]
    sink = open(cli.out, "a") if cli.out else None
    for qps in loads:
        # fresh server/fleet per point so histograms don't bleed across
        if cli.router:
            srvs, router = build_router_fleet(cli)
            try:
                row = run_router_point(router, srvs[-1], qps, cli.duration,
                                       cli.in_dim, cli.batch_frac)
            finally:
                router.close(stop_backends=True)
            row["router_replicas"] = cli.router
        else:
            srv = build_server(cli)
            try:
                row = run_load_point(srv, qps, cli.duration, cli.in_dim)
            finally:
                srv.stop()
        row["max_batch"] = cli.max_batch
        row["max_wait_us"] = cli.max_wait_us
        row["replicas"] = cli.replicas
        line = json.dumps(row)
        print(line, flush=True)
        if sink:
            sink.write(line + "\n")
            sink.flush()
    if sink:
        sink.close()


if __name__ == "__main__":
    main()

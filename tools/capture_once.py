"""One-process TPU capture for a flapping tunnel.

The round-5 tunnel pattern (PERF.md): when half-healthy, the FIRST
backend init in a window succeeds and later ones hang — so multi-process
orchestration (probe, then phase subprocesses) burns the window on the
probe. This script claims the chip ONCE and runs everything in that
process, fastest-first, appending one JSON line per result so a mid-run
tunnel death keeps everything already measured:

  1. probe (device matmul)                       ~seconds
  2. transformer-LM train step, flash backend    (the headline)
  3. flash kernel at s=8k and at model shapes
  4. splash oracle (ceiling calibration)
  5. ResNet-50 Module benchmark                  (cold compile ~60-90min,
                                                  cached in .jax_cache)

A watchdog hard-exits (code 3) if the backend init hangs >8min — a dead
tunnel costs minutes, and the process never wedges a watcher cycle.

Results stream to stdout AND to capture.jsonl under the telemetry
artifact dir (MXNET_TELEMETRY_DUMP_DIR) — never the working tree.

Usage: python tools/capture_once.py [--skip-resnet]
"""
import argparse
import os
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from artifact_io import tee_line  # noqa: E402


def emit(name, **kw):
    tee_line("capture.jsonl",
             {"capture": name, "t": round(time.time(), 1), **kw})


def emit_partial(reason):
    """A dead/flapping tunnel must still leave a machine-readable BENCH
    record: everything measured so far is already on stdout (one line per
    phase), so this marks the run explicitly incomplete — with whatever
    telemetry summary the process accumulated — instead of leaving a
    truncated log a reader has to diagnose.  Compile-time and
    compile-cache hit/miss counters ride along so even a run the tunnel
    killed mid-compile still yields cold-start evidence."""
    summary = None
    cache = None
    try:
        from mxnet_tpu import telemetry

        summary = telemetry.summary() or None
    except Exception:
        pass
    try:
        from mxnet_tpu import compile_cache

        cache = compile_cache.stats()
        if not any((cache["hits"], cache["misses"], cache["errors"])):
            cache = None
    except Exception:
        pass
    emit("partial", reason=reason, telemetry=summary, compile_cache=cache)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-resnet", action="store_true")
    cli = ap.parse_args()

    def _watchdog_fire():
        emit_partial("backend init watchdog fired (480s): tunnel dead")
        os._exit(3)

    watchdog = threading.Timer(480, _watchdog_fire)
    watchdog.daemon = True
    watchdog.start()

    import mxnet_tpu  # noqa: F401  (JAX_PLATFORMS honor + compile cache)
    import jax

    x = jax.numpy.ones((128, 128))
    (x @ x).block_until_ready()
    watchdog.cancel()
    backend = jax.default_backend()
    emit("probe", backend=backend,
         device=str(jax.devices()[0]))
    if backend != "tpu":
        emit_partial("backend %s is not tpu" % backend)
        emit("abort", reason="backend %s is not tpu" % backend)
        return 2

    import bench

    peak = 197e12
    errors = []
    try:
        lm = bench.transformer_lm_bench(attn_impl="flash")
        emit("transformer_lm_flash",
             tokens_per_sec=round(lm["tokens_per_sec"], 1),
             tflops=round(lm["model_tflops"], 2),
             mfu=round(lm["model_tflops"] * 1e12 / peak, 4))
    except Exception as e:
        errors.append("transformer_lm_flash")
        emit("transformer_lm_flash", error=str(e)[:200])

    from bench_attention import run_bench, run_oracle_bench

    for name, kw in (
            ("flash_kernel_8k", dict(seq=8192, steps=10, block_q=512,
                                     block_k=1024)),
            ("flash_kernel_model_shape", dict(batch=4, heads=16, seq=4096,
                                              steps=10, block_q=512,
                                              block_k=1024))):
        try:
            r = run_bench(**kw)
            emit(name, tflops=r["value"], mfu=r["mfu"],
                 step_ms=r["step_ms"])
        except Exception as e:
            errors.append(name)
            emit(name, error=str(e)[:200])
    try:
        orc = run_oracle_bench(seq=8192, steps=5)
        emit("splash_oracle", tflops=orc["value"], mfu=orc["mfu"])
    except Exception as e:
        errors.append("splash_oracle")
        emit("splash_oracle", error=str(e)[:200])

    if not cli.skip_resnet:
        try:
            rn = bench.resnet_bench(bench._arg_parser().parse_args([]))
            emit("resnet50", **{k: v for k, v in rn.items()
                                if k != "metric"})
        except Exception as e:
            errors.append("resnet50")
            emit("resnet50", error=str(e)[:300])
    if errors:
        # some phases died (usually the tunnel flapping mid-window): the
        # record set is explicitly partial, not a clean capture
        emit_partial("phase error(s): %s" % ", ".join(errors))
    emit("done", complete=not errors)
    return 0


if __name__ == "__main__":
    sys.exit(main())

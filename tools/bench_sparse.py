"""Sparse parameter plane throughput + worker-memory bench.

Two questions the row-sparse plane exists to answer:

* **rows/s**: how fast can a worker push+pull the touched rows of a
  1M x 64 embedding table versus pushing the equivalent FULL dense
  table through the dense kvstore path each step?
* **worker memory**: how do worker-resident parameter bytes scale as the
  logical table grows?  (Sparse: flat at O(touched); dense: linear.)

Runs entirely on CPU against in-process KVStoreServers (the payloads are
host numpy; claiming a TPU would measure nothing extra).  Emits ONE JSON
line (the bench.py record shape) as the last stdout line; wired into
bench.py as a CPU-only phase like bench_kvstore.py.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# force the in-process server path (a launcher-provided fleet would
# measure that fleet, not the plane)
for _v in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT", "DMLC_SERVER_URIS",
           "DMLC_ROLE"):
    os.environ.pop(_v, None)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir))


def run_sparse(num_rows, dim, touched, rounds, num_servers):
    """Best-of-N rows/s for one push_rows + pull_rows step of ``touched``
    rows against ``num_servers`` sharded in-process servers."""
    import numpy as np

    from mxnet_tpu.kvstore_server import ServerClient, start_server
    from mxnet_tpu.sparse.plane import SparseParamPlane

    srvs = [start_server(port=0) for _ in range(num_servers)]
    clients = [ServerClient(*s.addr) for s in srvs]
    try:
        plane = SparseParamPlane(clients)
        plane.init_table("emb", num_rows=num_rows, row_shape=(dim,),
                         init=("zeros",))
        rng = np.random.RandomState(7)
        grads = np.ones((touched, dim), dtype=np.float32)
        best = 0.0
        for rnd in range(rounds + 1):  # round 0: connection warmup
            ids = rng.randint(0, num_rows, size=touched).astype(np.int64)
            t0 = time.perf_counter()
            plane.push_rows("emb", ids, grads)
            plane.pull_rows("emb", ids)
            elapsed = time.perf_counter() - t0
            if rnd > 0:
                best = max(best, touched * 2 / elapsed)
        return best
    finally:
        for c in clients:
            try:
                c.stop_server()
            except Exception:
                pass
            c.close()


def run_dense(num_rows, dim, rounds):
    """Best-of-N full-table push+pull throughput expressed in rows/s —
    the cost the sparse plane avoids paying per step."""
    import numpy as np

    from mxnet_tpu.kvstore_server import ServerClient, start_server

    srv = start_server(port=0)
    c = ServerClient(*srv.addr)
    try:
        table = np.zeros((num_rows, dim), dtype=np.float32)
        c.init("emb", table)
        best = 0.0
        for rnd in range(rounds + 1):
            t0 = time.perf_counter()
            c.push("emb", table)
            c.pull("emb")
            elapsed = time.perf_counter() - t0
            if rnd > 0:
                best = max(best, num_rows * 2 / elapsed)
        return best
    finally:
        try:
            c.stop_server()
        except Exception:
            pass
        c.close()


def run_memory_sweep(dim, touched, table_sizes, num_servers):
    """Worker-resident parameter bytes vs logical table size: the sparse
    worker's footprint is its pull buffer (flat); dense is the table."""
    import numpy as np

    from mxnet_tpu.kvstore_server import ServerClient, start_server
    from mxnet_tpu.sparse.plane import SparseParamPlane

    srvs = [start_server(port=0) for _ in range(num_servers)]
    clients = [ServerClient(*s.addr) for s in srvs]
    out = []
    try:
        plane = SparseParamPlane(clients)
        rng = np.random.RandomState(11)
        for n in table_sizes:
            key = "emb_%d" % n
            plane.init_table(key, num_rows=n, row_shape=(dim,),
                             init=("zeros",))
            ids = rng.randint(0, n, size=touched).astype(np.int64)
            got = plane.pull_rows(key, ids)
            out.append({
                "table_rows": n,
                "logical_bytes": n * dim * 4,
                "sparse_worker_bytes": int(got.nbytes),
                "dense_worker_bytes": n * dim * 4,
            })
    finally:
        for c in clients:
            try:
                c.stop_server()
            except Exception:
                pass
            c.close()
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000,
                    help="logical table rows (the 1M x 64 headline config)")
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--touched", type=int, default=4096,
                    help="distinct rows touched per step")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--servers", type=int, default=2)
    ap.add_argument("--dense-rows", type=int, default=100_000,
                    help="dense full-table baseline size (kept smaller "
                    "than --rows so the baseline finishes; rows/s "
                    "normalizes the comparison)")
    cli = ap.parse_args(argv)

    sparse = run_sparse(cli.rows, cli.dim, cli.touched, cli.rounds,
                        cli.servers)
    dense = run_dense(cli.dense_rows, cli.dim, cli.rounds)
    sweep = run_memory_sweep(cli.dim, cli.touched,
                             [10_000, 100_000, cli.rows], cli.servers)

    flat = all(r["sparse_worker_bytes"] == sweep[0]["sparse_worker_bytes"]
               for r in sweep)
    # what each path costs PER STEP: sparse ships the touched rows, dense
    # ships the whole logical table (extrapolated from measured bulk rows/s)
    sparse_step_s = cli.touched * 2 / sparse if sparse else float("inf")
    dense_step_s = cli.rows * 2 / dense if dense else float("inf")
    record = {
        "metric": "sparse_pushpull_rows_per_s",
        "value": round(sparse, 1),
        "unit": "rows/s",
        # speedup of a sparse step over pushing the full table every step
        "vs_baseline": round(dense_step_s / sparse_step_s, 2),
        "sparse_rows_s": round(sparse, 1),
        "dense_fulltable_rows_s": round(dense, 1),
        "sparse_step_ms": round(sparse_step_s * 1e3, 2),
        "dense_fulltable_step_ms": round(dense_step_s * 1e3, 2),
        "table_rows": cli.rows,
        "dim": cli.dim,
        "touched": cli.touched,
        "servers": cli.servers,
        "worker_bytes_flat_vs_table": flat,
        "memory_sweep": sweep,
    }
    print(json.dumps(record))
    return record


if __name__ == "__main__":
    main()

"""Localhost kvstore push/pull throughput: sync vs async vs async+bucketed.

The workload the comm engine exists for: MANY SMALL KEYS (a model with
hundreds of bias/gamma/beta tensors), where the synchronous per-key path
pays one full RPC round trip per key, serialized.  Three modes over the
same in-process dist_async server (kvstore_server.py):

* ``sync``         — plain DistAsyncKVStore, blocking push/pull per key
                     (the pre-engine behavior)
* ``async``        — comm_engine.AsyncKVStore, bucketing off: per-key ops
                     overlap via the worker pool + pipelined ServerClient
* ``async_bucket`` — bucketing on: small keys coalesce into fused
                     multi-key RPCs (MXNET_KVSTORE_BUCKET_BYTES)

Emits ONE JSON line (the bench.py record shape) as the last stdout line;
wired into bench.py as a fast CPU-only phase so the perf trajectory gets
numbers even when the TPU tunnel is down.
"""
import argparse
import json
import os
import sys
import time

# CPU-only by design: the payloads are host numpy round trips; claiming
# the TPU would serialize against a training process for nothing
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# force the in-process server path (a launcher-provided fleet would
# measure that fleet, not the transport)
for _v in ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT", "DMLC_SERVER_URIS",
           "DMLC_ROLE"):
    os.environ.pop(_v, None)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir))


def _mk_store(mode, threads, bucket_bytes):
    from mxnet_tpu.comm_engine import make_async
    from mxnet_tpu.kvstore import DistAsyncKVStore

    kv = DistAsyncKVStore()
    if mode == "sync":
        return kv
    return make_async(kv, num_threads=threads,
                      bucket_bytes=bucket_bytes if mode == "async_bucket"
                      else 0)

def run_mode(mode, keys, key_size, rounds, threads, bucket_bytes):
    """Run ``rounds`` timed push-all/pull-all/wait rounds over ``keys``
    small keys; returns the best round's ops/s (one push or pull of one
    key == one op).  Best-of-N is the timeit convention: the minimum
    time is the workload's cost, the spread is scheduler noise."""
    import numpy as np

    import mxnet_tpu as mx

    kv = _mk_store(mode, threads, bucket_bytes)
    try:
        vals = [mx.nd.array(np.full(key_size, i % 7, dtype=np.float32))
                for i in range(keys)]
        outs = [mx.nd.zeros((key_size,)) for _ in range(keys)]
        for i in range(keys):
            kv.init(i, vals[i])
        best = 0.0
        for rnd in range(rounds + 1):  # round 0: connection+pool warmup
            t0 = time.perf_counter()
            for i in range(keys):
                kv.push(i, vals[i])
            for i in range(keys):
                kv.pull(i, outs[i])
            kv.wait_all()
            elapsed = time.perf_counter() - t0
            if rnd > 0:
                best = max(best, keys * 2 / elapsed)
        return best
    finally:
        kv.close()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1000,
                    help="number of small keys")
    ap.add_argument("--key-size", type=int, default=64,
                    help="elements per key (float32)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="timed push-all/pull-all rounds per mode")
    ap.add_argument("--threads", type=int, default=4,
                    help="comm-engine worker threads for the async modes")
    ap.add_argument("--bucket-bytes", type=int, default=1 << 16)
    cli = ap.parse_args(argv)

    sync = run_mode("sync", cli.keys, cli.key_size, cli.rounds,
                    cli.threads, cli.bucket_bytes)
    async_ = run_mode("async", cli.keys, cli.key_size, cli.rounds,
                      cli.threads, cli.bucket_bytes)
    bucket = run_mode("async_bucket", cli.keys, cli.key_size, cli.rounds,
                      cli.threads, cli.bucket_bytes)

    record = {
        "metric": "kvstore_pushpull_throughput",
        "value": round(bucket, 1),
        "unit": "ops/s",
        # baseline = the synchronous per-key path this PR replaces
        "vs_baseline": round(bucket / sync, 2) if sync else 0.0,
        "sync_ops_s": round(sync, 1),
        "async_ops_s": round(async_, 1),
        "async_bucket_ops_s": round(bucket, 1),
        "speedup_async": round(async_ / sync, 2) if sync else 0.0,
        "speedup_bucket": round(bucket / sync, 2) if sync else 0.0,
        "keys": cli.keys,
        "key_size": cli.key_size,
        "rounds": cli.rounds,
        "threads": cli.threads,
    }
    print(json.dumps(record))
    return record


if __name__ == "__main__":
    main()

"""Performance probe for the fused ResNet-50 train step.

Builds the exact benchmark Module (bench.py path), runs one step, then lowers
the SAME fused program and reports XLA cost analysis (flops, bytes), HLO op
histogram (how many transposes/copies survived), and measured step time.
Optionally dumps full HLO text and a jax.profiler trace.

Usage:
  python tools/perf_probe.py [--batch-size 256] [--dump-hlo /tmp/hlo.txt]
                             [--trace /tmp/jax-trace]
"""
import argparse
import collections
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.artifact_io import write_json  # noqa: E402


def build_module(batch):
    import mxnet_tpu as mx
    from examples.image_classification.common import fit
    from examples.image_classification.train_imagenet import get_network

    parser = argparse.ArgumentParser()
    fit.add_fit_args(parser)
    args = parser.parse_args([
        "--network", "resnet-50", "--num-classes", "1000",
        "--image-shape", "3,224,224", "--batch-size", str(batch),
        "--lr", "0.1", "--dtype", "bfloat16", "--benchmark", "1"])
    net = get_network(args)

    shape = (3, 224, 224)
    train = fit.SyntheticIter(shape, 1000, batch, num_batches=200)
    mod = mx.mod.Module(net, context=mx.current_context(),
                        compute_dtype="bfloat16")
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label, for_training=True)
    mod.init_params(initializer=mx.init.Xavier(factor_type="in",
                                               magnitude=2.34))
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "wd": 1e-4,
                                         "momentum": 0.9,
                                         "rescale_grad": 1.0 / batch})
    return mod, train


# re-exported for back-compat: the analysis now lives in the shared
# mxnet_tpu.hlo_analysis module (the autotuner uses it too)
from mxnet_tpu.hlo_analysis import bn_fusion_analysis  # noqa: E402,F401
from mxnet_tpu.hlo_analysis import hlo_op_counts  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--num-steps", type=int, default=20)
    ap.add_argument("--dump-hlo", default=None)
    ap.add_argument("--trace", default=None)
    cli = ap.parse_args()

    import jax

    mod, train = build_module(cli.batch_size)
    batch = train.next()

    def step():
        mod.forward_backward(batch)
        mod.update()

    t0 = time.time()
    step()
    ex = mod._exec_group.execs[0]
    # flush deferred fused batch so _fused_introspect exists
    mod._flush_fused_pending() if hasattr(mod, "_flush_fused_pending") else None
    compile_s = time.time() - t0

    fn, abstract = getattr(ex, "_fused_introspect", (None, None))
    report = {"batch_size": cli.batch_size, "compile_s": round(compile_s, 1)}
    if fn is not None and hasattr(fn, "lower"):
        # same analysis path StepMonitor uses per compiled executable, so
        # the probe's numbers and live telemetry MFU agree by construction
        from mxnet_tpu import telemetry
        try:
            compiled, info = telemetry.lower_and_analyze(fn, abstract)
            report["xla_flops"] = info.get("flops")
            report["xla_bytes_accessed"] = info.get("bytes_accessed")
        except Exception as e:  # noqa
            report["cost_analysis_error"] = str(e)
            compiled = fn.lower(*abstract).compile()
        hlo = compiled.as_text()
        report["hlo_op_counts"] = hlo_op_counts(
            hlo, interesting=("transpose", "copy", "convolution", "fusion",
                              "custom-call", "all-reduce", "reshape",
                              "bitcast", "dot"))
        # count convs whose operand/result types are bf16
        convs = re.findall(r"= (\S+) convolution\(", hlo)
        report["conv_result_dtypes"] = dict(collections.Counter(
            c.split("[")[0] for c in convs))
        report["bn_fusion"] = bn_fusion_analysis(hlo)
        if cli.dump_hlo:
            with open(cli.dump_hlo, "w") as f:
                f.write(hlo)

    # steady-state timing
    for _ in range(3):
        step()
    ex2 = mod._exec_group.execs[0]
    name = mod._exec_group.param_names[-1]
    ex2.arg_dict[name].asnumpy()
    if cli.trace:
        jax.profiler.start_trace(cli.trace)
    t0 = time.time()
    for _ in range(cli.num_steps):
        step()
    ex2.arg_dict[name].asnumpy()
    dt = time.time() - t0
    if cli.trace:
        jax.profiler.stop_trace()
    report["step_ms"] = round(1000 * dt / cli.num_steps, 2)
    report["img_per_sec"] = round(cli.batch_size * cli.num_steps / dt, 1)
    if report.get("xla_flops"):
        # measured MFU from XLA's own flop count, same denominator as the
        # live telemetry gauge (MXNET_TELEMETRY_PEAK_FLOPS-overridable)
        from mxnet_tpu import telemetry
        report["mfu_xla_flops"] = round(
            report["xla_flops"] / (dt / cli.num_steps)
            / telemetry.peak_flops(), 4)
    write_json("perf_probe.json", report)


if __name__ == "__main__":
    main()

"""Collective-bandwidth microbenchmark for the dist data plane.

TPU-native equivalent of the reference's kvstore throughput harness
(/root/reference/tools/bandwidth/measure.py): instead of timing ps-lite
push/pull round trips, it times the compiled allreduce the kvstore (and the
fused step's psum) actually runs, across a sweep of tensor sizes.

Run under the launcher, one process per worker:
  python tools/launch.py -n 4 python tools/bandwidth.py [--sizes-mb 1,4,16,64]
Prints one JSON line per size on rank 0 with effective algorithm bandwidth
(2*(n-1)/n * bytes / time, the standard allreduce accounting).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes-mb", default="1,4,16,64")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--kv-store", default="dist_sync",
                    choices=["dist_sync", "dist_async"],
                    help="dist_async measures the TCP parameter-server "
                         "push+pull path (launch with -s servers)")
    cli = ap.parse_args()

    import numpy as np
    import mxnet_tpu as mx

    kv = mx.kvstore.create(cli.kv_store)
    rank, n = kv.rank, kv.num_workers

    for i, size_mb in enumerate(float(s) for s in cli.sizes_mb.split(",")):
        nelem = int(size_mb * 1e6 / 4)
        arr = mx.nd.ones((nelem,)) * (rank + 1)
        kv.init(100 + i, mx.nd.zeros((nelem,)))
        # warm up (compile)
        kv.push(100 + i, arr)
        out = mx.nd.zeros((nelem,))
        kv.pull(100 + i, out=out)
        out.asnumpy()
        kv._barrier()
        t0 = time.time()
        for _ in range(cli.iters):
            kv.push(100 + i, arr)
        kv.pull(100 + i, out=out)
        out.asnumpy()
        dt = (time.time() - t0) / cli.iters
        expect = (n * (n + 1)) // 2  # sum of (rank+1): init 0 + iters pushes
        if cli.kv_store == "dist_sync":
            # standard allreduce bus accounting
            bw = 2 * (n - 1) / n * size_mb * 1e6 / dt
            metric = "allreduce_bandwidth"
        else:
            # parameter-server path: bytes pushed per timed iteration
            bw = size_mb * 1e6 / dt
            metric = "ps_push_bandwidth"
        if rank == 0:
            print(json.dumps({
                "metric": metric, "size_mb": size_mb,
                "workers": n, "time_ms": round(dt * 1e3, 3),
                "bus_gb_s": round(bw / 1e9, 3),
                "unit": "GB/s"}), flush=True)
    if rank == 0:
        print("bandwidth OK", flush=True)


if __name__ == "__main__":
    main()

"""Single-artifact predict bundle — the amalgamation story, TPU-era.

Reference: ``amalgamation/`` concatenates the whole predict path into
one ``mxnet_predict-all.cc`` so a model can be embedded with zero build
dependencies (``amalgamation/README.md:1-14``). The equivalent property
here — "one file you copy next to a checkpoint and run anywhere the
runtime exists" — is a zipapp: this tool packs ``mxnet_tpu`` (pure
Python; the native .so fast paths are optional accelerators, not
dependencies) plus a predict ``__main__`` into ``mxtpu_predict.pyz``.

    python tools/amalgamate.py -o mxtpu_predict.pyz
    python mxtpu_predict.pyz --prefix model --epoch 3 \
        --input data.npy [--output out.npy] [--topk 5]

The bundle needs only the environment's python + jax/numpy (the same
runtime contract the reference's amalgamated .cc had on BLAS).
"""
import argparse
import os
import sys
import zipapp
import zipfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_MAIN = '''\
"""mxtpu_predict bundle entry: load a checkpoint, classify an input."""
import argparse
import sys


def main():
    ap = argparse.ArgumentParser(prog="mxtpu_predict.pyz")
    ap.add_argument("--prefix", required=True,
                    help="checkpoint prefix (prefix-symbol.json + "
                         "prefix-NNNN.params)")
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--input", required=True,
                    help=".npy array for the 'data' input")
    ap.add_argument("--data-name", default="data")
    ap.add_argument("--output", default=None,
                    help="write the full output array here (.npy)")
    ap.add_argument("--topk", type=int, default=5)
    args = ap.parse_args()

    import numpy as np

    import mxnet_tpu as mx

    x = np.load(args.input)
    pred = mx.predictor.Predictor.from_checkpoint(
        args.prefix, args.epoch, {args.data_name: x.shape})
    pred.forward(**{args.data_name: x})
    out = pred.get_output(0)
    out = out.asnumpy() if hasattr(out, "asnumpy") else np.asarray(out)
    if args.output:
        np.save(args.output, out)
    flat = out.reshape(out.shape[0], -1)
    for row in flat:
        top = np.argsort(row)[::-1][:args.topk]
        print(" ".join("%d:%.4f" % (i, row[i]) for i in top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
'''


def build(output, compress=True):
    """Pack mxnet_tpu + the predict __main__ into a zipapp."""
    import io
    import py_compile  # noqa: F401  (documents the pure-python contract)

    buf_dir = output + ".staging.zip"
    pkg = os.path.join(ROOT, "mxnet_tpu")
    comp = zipfile.ZIP_DEFLATED if compress else zipfile.ZIP_STORED
    with zipfile.ZipFile(buf_dir, "w", comp) as z:
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if not fn.endswith(".py"):
                    continue  # the .so fast paths are optional; the
                    # bundle ships the pure-python package only
                full = os.path.join(dirpath, fn)
                z.write(full, os.path.relpath(full, ROOT))
        z.writestr("__main__.py", _MAIN)
    # zipapp prepends the shebang and validates __main__
    zipapp.create_archive(buf_dir, output,
                          interpreter="/usr/bin/env python3")
    os.remove(buf_dir)
    return output


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default="mxtpu_predict.pyz")
    cli = ap.parse_args()
    out = build(cli.output)
    print("wrote %s (%.1f KB)" % (out, os.path.getsize(out) / 1024.0))


if __name__ == "__main__":
    main()

"""Inspect mxnet_tpu telemetry artifacts from the command line.

Two subcommands::

    python tools/telemetry_dump.py events run/events.jsonl [--tail 20]
        Pretty-print a structured-event JSONL log (one event per line:
        timestamp, kind, then the event's own fields).

    python tools/telemetry_dump.py trace a.json b.json -o merged.json
        Merge one or more Chrome-trace JSON files (dump_profile or
        telemetry.dump_trace output) into a single timeline, schema-check
        every event, and write the result — load it at chrome://tracing
        or https://ui.perfetto.dev.

Both read plain files: no framework import is needed for ``events``, so
the tool works on logs copied off a TPU host.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_events(path):
    events = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                print("%s:%d: unparseable line skipped" % (path, lineno),
                      file=sys.stderr)
    return events


def cmd_events(cli):
    events = _load_events(cli.file)
    if cli.tail:
        events = events[-cli.tail:]
    if not events:
        print("(no events)")
        return 0
    t0 = events[0].get("ts", 0.0)
    for ev in events:
        ts = ev.get("ts", 0.0)
        kind = ev.get("kind", "?")
        rest = {k: v for k, v in ev.items() if k not in ("ts", "kind")}
        fields = " ".join("%s=%s" % (k, rest[k]) for k in sorted(rest))
        print("+%9.3fs  %-16s %s" % (ts - t0, kind, fields))
    print("-- %d event(s), %d kind(s)"
          % (len(events), len({e.get("kind") for e in events})))
    return 0


def merge_traces(paths):
    """Merge per-process chrome-trace dumps into ONE fleet timeline.

    Each input file gets its own synthetic pid (its index), every event is
    rewritten onto that pid, and each file's ``process_name`` metadata —
    the role/rank label (``worker0``, ``server0``) the tracer stamped at
    dump time — names the process track.  Flow events keep their ids
    untouched, so a worker-side ``"s"`` and the server-side ``"f"`` with
    the same distributed trace id draw an arrow ACROSS process tracks.
    Returns the merged payload dict (raises on an unreadable input)."""
    out = []
    seen = set()
    for pid, path in enumerate(paths):
        with open(path) as f:
            payload = json.load(f)
        evs = payload.get("traceEvents", payload) \
            if isinstance(payload, dict) else payload
        if not isinstance(evs, list):
            raise ValueError("%s: not a chrome-trace file" % path)
        for ev in evs:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)  # never mutate the loaded payload
            ev["pid"] = pid
            if ev.get("ph") == "M":
                # per-process metadata dedup: one process_name per pid,
                # one thread_name per (pid, tid)
                key = (pid, ev.get("name"), ev.get("tid"),
                       json.dumps(ev.get("args", {}), sort_keys=True))
                if key in seen:
                    continue
                seen.add(key)
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def cmd_trace(cli):
    from mxnet_tpu import telemetry

    try:
        payload = merge_traces(cli.files)
    except (ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 1
    telemetry.validate_trace(payload)
    with open(cli.output, "w") as f:
        json.dump(payload, f)
    out = payload["traceEvents"]
    spans = sum(1 for e in out if e.get("ph") == "X")
    tids = {(e.get("pid"), e.get("tid")) for e in out if e.get("ph") == "X"}
    procs = sorted(e["args"].get("name", "?") for e in out
                   if e.get("ph") == "M" and e.get("name") == "process_name")
    print("wrote %s: %d span(s) across %d thread track(s), processes: %s"
          % (cli.output, spans, len(tids), ", ".join(procs) or "(none)"))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    ev = sub.add_parser("events", help="pretty-print an events.jsonl log")
    ev.add_argument("file")
    ev.add_argument("--tail", type=int, default=0,
                    help="only the last N events")
    tr = sub.add_parser("trace",
                        help="merge + validate chrome-trace JSON files")
    tr.add_argument("files", nargs="+")
    tr.add_argument("-o", "--output", required=True)
    cli = ap.parse_args(argv)
    return cmd_events(cli) if cli.cmd == "events" else cmd_trace(cli)


if __name__ == "__main__":
    sys.exit(main())

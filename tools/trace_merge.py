"""Stitch per-process telemetry trace dumps into ONE fleet chrome trace.

A cluster run with ``MXNET_TELEMETRY=1`` and ``MXNET_TELEMETRY_DIR`` set
leaves one ``trace-<role><rank>.json`` per process (workers, servers, the
launcher).  This tool merges them into a single timeline with one process
track per input file — named by the role/rank label each dump carries —
and validates the result against the chrome-trace schema::

    python tools/trace_merge.py -o fleet.json run/trace-*.json

Open the output at chrome://tracing or https://ui.perfetto.dev: kvstore
RPC spans on a ``workerN`` track connect by flow arrows to their handler
spans on the ``serverM`` track (same distributed trace id).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.telemetry_dump import merge_traces  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+",
                    help="per-process chrome-trace JSON dumps")
    ap.add_argument("-o", "--output", required=True,
                    help="merged fleet trace path")
    cli = ap.parse_args(argv)

    from mxnet_tpu import telemetry

    try:
        payload = merge_traces(cli.files)
    except (ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 1
    telemetry.validate_trace(payload)
    with open(cli.output, "w") as f:
        json.dump(payload, f)
    evs = payload["traceEvents"]
    procs = sorted(e["args"].get("name", "?") for e in evs
                   if e.get("ph") == "M" and e.get("name") == "process_name")
    flows = sum(1 for e in evs if e.get("ph") in ("s", "f"))
    print("wrote %s: %d event(s), %d flow arrow(s), process tracks: %s"
          % (cli.output, len(evs), flows, ", ".join(procs) or "(none)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Launch distributed training jobs as local processes.

Parity surface: /root/reference/tools/launch.py (dmlc-core tracker) —
``launch.py -n 4 python train.py ...`` spawns N worker processes with the
DMLC env-var contract set; ``-s K`` additionally spawns K parameter-server
processes (``dist_async``: the DMLC_ROLE=server import bootstrap in
mxnet_tpu/kvstore_server.py takes over in those).  For ``dist_sync`` no
servers are needed — workers rendezvous through the jax.distributed
coordinator at DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT (kvstore_dist.py).

Launchers: ``local`` (processes on this host) and ``ssh`` (one process
per entry of ``--hostfile``, reference tools/launch.py ssh mode — the mode
that maps to TPU-VM fleets, which are plain Linux hosts).  The reference's
mpi/sge/yarn modes are intentionally out of scope: XLA collectives replace
MPI, and pod slices are provisioned by the cloud control plane, not a
Hadoop-era batch queue (see docs/how_to/deviations.md).
"""
from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def parse_elastic(spec):
    """``MIN:MAX`` (or a bare ``MIN``, meaning MIN:MIN) -> (min, max).
    The job keeps running while at least MIN workers are live and
    respawns grow it back toward MAX."""
    lo, sep, hi = spec.partition(":")
    try:
        mn = int(lo)
        mx = int(hi) if sep else mn
    except ValueError:
        raise ValueError("--elastic expects MIN:MAX, got %r" % (spec,))
    if mn < 1 or mx < mn:
        raise ValueError("--elastic needs 1 <= MIN <= MAX, got %r" % (spec,))
    return mn, mx


def respawn_delay(attempt, base=1.0, cap=30.0, jitter=0.3, rand=None):
    """Exponential backoff with multiplicative jitter between respawn
    attempts (``attempt`` counts from 1): a persistently-crashing
    process must not be relaunched in a tight loop, and the jitter
    decorrelates a fleet of respawns hammering one coordinator."""
    import random

    r = (rand if rand is not None else random.random)()
    return min(cap, base * (2 ** (attempt - 1))) * (1.0 + jitter * r)


def _local_ip():
    """A routable address for DMLC_PS_ROOT_URI in ssh mode (the UDP-connect
    trick; no packet is sent)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 53))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _ssh_popen(host, env, command, ssh_port, cwd, extra_keys=()):
    """One remote process: env inlined into the remote command line (ssh
    does not forward the environment), cwd mirrored (the reference's ssh
    tracker does the same 'cd <pwd> && env ... cmd').  extra_keys carries
    the --env entries so 'every process' includes remote ones."""
    pass_keys = [k for k in env
                 if k.startswith(("DMLC_", "MXNET_"))
                 or k in ("PYTHONPATH", "JAX_PLATFORMS")
                 or k in extra_keys]
    env_str = " ".join("%s=%s" % (k, shlex.quote(env[k]))
                       for k in sorted(set(pass_keys)))
    remote = "cd %s && env %s %s" % (
        shlex.quote(cwd), env_str,
        " ".join(shlex.quote(c) for c in command))
    return subprocess.Popen(
        ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(ssh_port),
         host, remote])


def serving_main(argv):
    """``launch.py --serving``: one warm serving-replica process — the
    autoscaler's scale-out actuator (ProcessProvider) and the unit a
    cluster scheduler would run per pod.  Restores the checkpoint with
    its AOT bundle / compile cache attached (warm start: first request
    runs with zero cold buckets), serves HTTP, registers + heartbeats
    into the replica registry so every replicated router discovers it,
    and installs the SIGTERM preemption handler — scale-in retirement
    and cluster preemption are the same drain → deregister →
    postmortem → exit path."""
    import json
    import time

    parser = argparse.ArgumentParser(
        description="Launch one registered serving replica")
    parser.add_argument("--serving", action="store_true")
    parser.add_argument("--registry", required=True,
                        help="replica-registry address (host:port)")
    parser.add_argument("--name", required=True,
                        help="registry member name for this replica")
    parser.add_argument("--prefix", required=True,
                        help="checkpoint prefix (save_checkpoint files)")
    parser.add_argument("--epoch", type=int, required=True)
    parser.add_argument("--input-shapes", required=True,
                        help='JSON {input_name: [batch, ...]} shapes')
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, default=0)
    parser.add_argument("--no-aot", action="store_true",
                        help="serve without attaching the AOT bundle "
                             "(cold warmup compiles)")
    args = parser.parse_args(argv)

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from mxnet_tpu.serving import (InferenceServer, RegistryClient,
                                   install_preemption_handler,
                                   start_heartbeater)

    shapes = {k: tuple(v)
              for k, v in json.loads(args.input_shapes).items()}
    server = InferenceServer.from_checkpoint(
        args.prefix, args.epoch, shapes, attach_aot=not args.no_aot)
    host, port = server.serve_http(args.host, args.http_port)[:2]
    backend = "%s:%d" % (host, port)
    registry = RegistryClient(args.registry)
    stop_beat = start_heartbeater(registry, args.name, backend)
    install_preemption_handler(server, deregister=stop_beat)
    print("launch.py: serving replica %s at %s (cold_bucket_runs=%d)"
          % (args.name, backend, server.cold_bucket_runs()),
          file=sys.stderr, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        stop_beat()
        server.stop(drain=True)


def main():
    if "--serving" in sys.argv[1:]:
        serving_main(sys.argv[1:])
        return
    parser = argparse.ArgumentParser(
        description="Launch a distributed job locally",
        usage="launch.py [-h] -n NUM_WORKERS [-s NUM_SERVERS] command ...")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0)
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("-H", "--hostfile", type=str, default=None,
                        help="one host per line (ssh launcher); workers "
                             "and servers round-robin over the hosts")
    parser.add_argument("--ssh-port", type=int, default=22)
    parser.add_argument("--env", action="append", default=[],
                        help="extra KEY=VALUE env entries for every process")
    parser.add_argument("--auto-resume", type=int, default=0, metavar="N",
                        help="relaunch a worker that exits nonzero, up to N "
                             "times per worker (checkpoint-based fault "
                             "tolerance: the training script resumes via "
                             "mx.model.find_latest_checkpoint)")
    parser.add_argument("--metrics-port", type=int, default=0, metavar="P",
                        help="host a fleet metrics aggregator on this port: "
                             "every process pushes its telemetry registry "
                             "(MXNET_TELEMETRY_AGG_ADDR is exported) and "
                             "GET /metrics serves one Prometheus page with "
                             "role/rank labels plus fleet-derived gauges")
    parser.add_argument("--elastic", type=str, default=None,
                        metavar="MIN:MAX",
                        help="elastic membership: workers join the kvstore "
                             "server's live-rank table "
                             "(MXNET_KVSTORE_ELASTIC=1), the job keeps "
                             "running while at least MIN workers are live, "
                             "and auto-resume respawns rejoin as FRESH "
                             "ranks (mid-run join) growing back toward MAX")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")
    elastic = None
    if args.elastic is not None:
        try:
            elastic = parse_elastic(args.elastic)
        except ValueError as e:
            parser.error(str(e))
        if not (elastic[0] <= args.num_workers <= elastic[1]):
            parser.error("--elastic %s must bracket -n %d"
                         % (args.elastic, args.num_workers))

    hosts = None
    if args.launcher == "ssh":
        if not args.hostfile:
            parser.error("--launcher ssh requires --hostfile")
        with open(args.hostfile) as f:
            stripped = [ln.strip() for ln in f]
        hosts = [ln for ln in stripped if ln and not ln.startswith("#")]
        if not hosts:
            parser.error("hostfile %s is empty" % args.hostfile)

    # ssh mode: the rendezvous endpoint (jax.distributed coordinator) is
    # hosted by worker 0, which lands on the FIRST hostfile entry — the
    # launcher machine itself may not run any process at all. Strip any
    # user@ login prefix: ssh accepts it, coordinator_address cannot.
    default_uri = hosts[0].rsplit("@", 1)[-1] if hosts else "127.0.0.1"
    port = os.environ.get("DMLC_PS_ROOT_PORT") or str(_free_port())
    base_env = dict(os.environ)
    base_env.update({
        "DMLC_PS_ROOT_URI": os.environ.get("DMLC_PS_ROOT_URI", default_uri),
        "DMLC_PS_ROOT_PORT": port,
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    })
    if elastic is not None:
        base_env["MXNET_KVSTORE_ELASTIC"] = "1"
    if hosts is not None and args.num_servers > 0:
        # ssh mode places server i on hosts[i % len]; workers cannot derive
        # that from root_uri+port alone, so publish the authoritative
        # address list (server i binds, clients connect, from this)
        base_env["DMLC_SERVER_URIS"] = ",".join(
            "%s:%d" % (hosts[i % len(hosts)], int(port) + i)
            for i in range(args.num_servers))
    extra_keys = tuple(kv.partition("=")[0] for kv in args.env)
    for kv in args.env:
        k, _, v = kv.partition("=")
        base_env[k] = v

    aggregator = None
    if args.metrics_port:
        # fleet metrics: the launcher hosts the aggregation endpoint so it
        # outlives any single worker; processes push their registries to it
        # (telemetry.distributed.start_pusher reads the exported address)
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from mxnet_tpu.telemetry.distributed import FleetAggregator

        agg_host = _local_ip() if hosts is not None else "127.0.0.1"
        aggregator = FleetAggregator(host=agg_host, port=args.metrics_port)
        aggregator.start()
        base_env["MXNET_TELEMETRY_AGG_ADDR"] = aggregator.addr
        print("launch.py: fleet metrics at http://%s/metrics"
              % aggregator.addr, file=sys.stderr, flush=True)

    def spawn(env, rank):
        if hosts is None:
            return subprocess.Popen(args.command, env=env)
        return _ssh_popen(hosts[rank % len(hosts)], env, args.command,
                          args.ssh_port, os.getcwd(), extra_keys)

    procs = []
    server_procs = []
    worker_envs = []
    server_envs = []
    try:
        for i in range(args.num_servers):
            env = dict(base_env)
            env["DMLC_ROLE"] = "server"
            env["DMLC_SERVER_ID"] = str(i)
            server_envs.append(env)
            server_procs.append(spawn(env, i))
        for i in range(args.num_workers):
            env = dict(base_env)
            env["DMLC_ROLE"] = "worker"
            env["DMLC_WORKER_ID"] = str(i)
            worker_envs.append(env)
            procs.append(spawn(env, i))
        rc = 0
        if args.auto_resume or elastic is not None:
            # supervise: a crashed worker comes back (its script resumes
            # from the newest checkpoint) and a crashed SERVER comes back
            # too (restoring its state from MXNET_KVSTORE_SNAPSHOT_PATH if
            # configured — workers ride out the outage through their
            # idempotent-retry transport, no worker restarts needed);
            # clean exits retire normally.  Respawns wait out an
            # exponential backoff with jitter (respawn_delay) so a
            # persistently-crashing process is not relaunched in a tight
            # loop.  --elastic additionally tolerates shrink (the job
            # continues while >= MIN workers are live) and respawns join
            # as FRESH ranks, growing back toward MAX.
            import time

            attempts = [0] * args.num_workers
            srv_attempts = [0] * args.num_servers
            live = dict(enumerate(procs))
            pending = {}      # worker slot -> (ready_at, env, rank)
            srv_pending = {}  # server idx -> (ready_at, env)
            next_rank = args.num_workers

            def n_live():
                return len(live) + len(pending)

            while live or pending:
                time.sleep(0.2)
                now = time.monotonic()
                for i, (t, env) in list(srv_pending.items()):
                    if now >= t:
                        del srv_pending[i]
                        server_procs[i] = spawn(env, i)
                for i, p in list(enumerate(server_procs)):
                    if i in srv_pending:
                        continue
                    r = p.poll()
                    if r is None or r == 0:
                        continue
                    if srv_attempts[i] >= args.auto_resume:
                        continue
                    srv_attempts[i] += 1
                    env = dict(server_envs[i])
                    env["MXNET_AUTORESUME_ATTEMPT"] = str(srv_attempts[i])
                    delay = respawn_delay(srv_attempts[i])
                    print("launch.py: server %d exited rc=%d; relaunch "
                          "%d/%d in %.1fs (%d attempts left)"
                          % (i, r, srv_attempts[i], args.auto_resume,
                             delay, args.auto_resume - srv_attempts[i]),
                          file=sys.stderr, flush=True)
                    srv_pending[i] = (now + delay, env)
                for slot, (t, env, rank) in list(pending.items()):
                    if now >= t:
                        del pending[slot]
                        p2 = spawn(env, rank)
                        live[slot] = p2
                        procs.append(p2)
                for i, p in list(live.items()):
                    r = p.poll()
                    if r is None:
                        continue
                    del live[i]
                    if r != 0 and attempts[i] < args.auto_resume and \
                            (elastic is None or n_live() < elastic[1]):
                        attempts[i] += 1
                        env = dict(worker_envs[i])
                        env["MXNET_AUTORESUME_ATTEMPT"] = str(attempts[i])
                        # rejoin contract (reference kvstore_dist.h:35-38):
                        # recovered workers skip startup barriers
                        env["DMLC_IS_RECOVERY"] = "1"
                        rank = i
                        if elastic is not None:
                            # a preempted rank never comes back as itself
                            # — the server may already have evicted it —
                            # so the respawn joins mid-run as a FRESH rank
                            rank = next_rank
                            next_rank += 1
                            env["DMLC_WORKER_ID"] = str(rank)
                            env["MXNET_KVSTORE_ELASTIC_JOIN"] = "1"
                        delay = respawn_delay(attempts[i])
                        print("launch.py: worker %d exited rc=%d; relaunch"
                              " %d/%d as rank %d in %.1fs (%d attempts "
                              "left)" % (i, r, attempts[i],
                                         args.auto_resume, rank, delay,
                                         args.auto_resume - attempts[i]),
                              file=sys.stderr, flush=True)
                        pending[i] = (now + delay, env, rank)
                    elif elastic is not None and r != 0 and \
                            n_live() >= elastic[0]:
                        # preemption the job absorbs: the fleet shrank but
                        # stays at or above MIN — not a job failure
                        print("launch.py: worker %d retired rc=%d; "
                              "continuing elastically with %d live "
                              "(min %d)" % (i, r, n_live(), elastic[0]),
                              file=sys.stderr, flush=True)
                    else:
                        rc = rc or r
        else:
            for p in procs:
                p.wait()
                rc = rc or p.returncode
    finally:
        # grace period before the TERM sweep: servers that exit on their
        # own (cooperative-stop command, or short-lived stub programs in
        # tests) must not race the teardown — without this a server
        # process spawned moments ago can be killed before it ever runs
        import time

        deadline = time.monotonic() + 1.0
        for p in server_procs:
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.02)
        for p in procs + server_procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in server_procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        if aggregator is not None:
            aggregator.stop()
    sys.exit(rc)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Launch distributed training jobs as local processes.

Parity surface: /root/reference/tools/launch.py (dmlc-core tracker) —
``launch.py -n 4 python train.py ...`` spawns N worker processes with the
DMLC env-var contract set; ``-s K`` additionally spawns K parameter-server
processes (``dist_async``: the DMLC_ROLE=server import bootstrap in
mxnet_tpu/kvstore_server.py takes over in those).  For ``dist_sync`` no
servers are needed — workers rendezvous through the jax.distributed
coordinator at DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT (kvstore_dist.py).

Only the ``local`` launcher is implemented: on TPU pods the platform
scheduler (GKE/XPK) starts one process per host with the same env contract,
so ssh/mpi/sge/yarn modes of the reference are intentionally out of scope.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job locally",
        usage="launch.py [-h] -n NUM_WORKERS [-s NUM_SERVERS] command ...")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0)
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local"])
    parser.add_argument("--env", action="append", default=[],
                        help="extra KEY=VALUE env entries for every process")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    port = os.environ.get("DMLC_PS_ROOT_PORT") or str(_free_port())
    base_env = dict(os.environ)
    base_env.update({
        "DMLC_PS_ROOT_URI": os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
        "DMLC_PS_ROOT_PORT": port,
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
    })
    for kv in args.env:
        k, _, v = kv.partition("=")
        base_env[k] = v

    procs = []
    server_procs = []
    try:
        for i in range(args.num_servers):
            env = dict(base_env)
            env["DMLC_ROLE"] = "server"
            env["DMLC_SERVER_ID"] = str(i)
            server_procs.append(subprocess.Popen(args.command, env=env))
        for i in range(args.num_workers):
            env = dict(base_env)
            env["DMLC_ROLE"] = "worker"
            env["DMLC_WORKER_ID"] = str(i)
            procs.append(subprocess.Popen(args.command, env=env))
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
    finally:
        for p in procs + server_procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in server_procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
    sys.exit(rc)


if __name__ == "__main__":
    main()

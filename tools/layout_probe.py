"""Microbenchmark: conv train-step (fwd+bwd) in NCHW vs NHWC logical layout
on representative ResNet-50 shapes, pure JAX, bf16.  Quantifies what layout
conversion is worth before touching the framework ops.
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.hlo_analysis import peak_flops  # noqa: E402

B = int(sys.argv[1]) if len(sys.argv) > 1 else 256

# (C_in, C_out, H, kernel, stride) — one per ResNet-50 stage flavor
SHAPES = [
    (3, 64, 224, 7, 2),      # stem
    (64, 64, 56, 1, 1),      # 1x1
    (64, 64, 56, 3, 1),      # 3x3 stage1
    (256, 128, 56, 1, 2),    # downsample 1x1
    (128, 128, 28, 3, 1),    # 3x3 stage2
    (256, 256, 14, 3, 1),    # 3x3 stage3
    (512, 512, 7, 3, 1),     # 3x3 stage4
]


def bench(layout):
    total = 0.0
    flops = 0.0
    for ci, co, h, k, s in SHAPES:
        pad = (k - 1) // 2
        if layout == "NCHW":
            x = jnp.zeros((B, ci, h, h), jnp.bfloat16)
            w = jnp.zeros((co, ci, k, k), jnp.bfloat16)
            dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NCHW", "OIHW", "NCHW"))
        else:
            x = jnp.zeros((B, h, h, ci), jnp.bfloat16)
            w = jnp.zeros((k, k, ci, co), jnp.bfloat16)
            dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NHWC", "HWIO", "NHWC"))

        def loss(x, w):
            y = lax.conv_general_dilated(x, w, (s, s), [(pad, pad)] * 2,
                                         dimension_numbers=dn)
            return jnp.sum(y.astype(jnp.float32))

        g = jax.jit(jax.grad(loss, argnums=(0, 1)))
        r = g(x, w)
        jax.block_until_ready(r)
        n = 20
        t0 = time.time()
        for _ in range(n):
            r = g(x, w)
        jax.block_until_ready(r)
        dt = (time.time() - t0) / n
        ho = h // s
        f = 3 * 2 * B * co * ci * k * k * ho * ho  # fwd+bwd ~ 3x fwd MACs*2
        total += dt
        flops += f
        print(f"  {layout} ci={ci} co={co} h={h} k={k} s={s}: "
              f"{dt*1e3:.2f} ms  {f/dt/1e12:.1f} TF/s", flush=True)
    return total, flops


for layout in ("NCHW", "NHWC"):
    t, f = bench(layout)
    print(json.dumps({"layout": layout, "total_ms": round(t * 1e3, 2),
                      "tflops": round(f / t / 1e12, 1),
                      "mfu": round(f / t / peak_flops(), 3)}))

"""Flash-attention kernel benchmark — the framework's high-MFU path.

Times a jitted causal-attention TRAIN step (fwd + the Pallas backward
kernels) at transformer shapes, reporting achieved TFLOP/s and MFU against
the chip's bf16 peak. Causal attention FLOPs are counted as
0.5 * (4*b*h*s^2*d) forward + 2x that for backward (dQ + dK/dV each
recompute P), i.e. 3x forward — the same accounting PERF.md uses.

Usage: python tools/bench_attention.py [--seq 16384] [--steps 10]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_bench(batch=1, heads=8, head_dim=128, seq=16384, steps=10,
              block_q=512, block_k=1024):
    """Time the causal flash-attention train step; returns the record dict.
    Importable so bench.py can measure in-process (the TPU is held by one
    process — a subprocess could not claim it)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops.attention import flash_attention

    on_tpu = jax.default_backend() == "tpu"
    b, h, d = batch, heads, head_dim
    s = seq if on_tpu else min(seq, 512)
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, s, h, d), dt) * 0.1
    k = jax.random.normal(key, (b, s, h, d), dt) * 0.1
    v = jax.random.normal(key, (b, s, h, d), dt) * 0.1

    def loss(q, k, v):
        # block_q/block_k None defers to resolve_blocks (autotuned when
        # MXNET_AUTOTUNE is on, built-in defaults otherwise)
        o = flash_attention(q, k, v, causal=True, block_q=block_q,
                            block_k=block_k)
        return jnp.mean(o.astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    chain = jax.jit(lambda q, dq: q + 0 * dq)  # data-dependence between steps

    g = step(q, k, v)
    q = chain(q, g[0])
    np.asarray(q[0, 0, 0, 0])
    t0 = time.time()
    for _ in range(steps):
        g = step(q, k, v)
        q = chain(q, g[0])
    np.asarray(q[0, 0, 0, 0])
    dt_s = (time.time() - t0) / steps

    fwd_flops = 0.5 * 4.0 * b * h * s * s * d  # causal: half the s^2 grid
    total = 3.0 * fwd_flops
    peak = 197e12 if on_tpu else None
    return {
        "metric": "flash_attention_train_tflops",
        "value": round(total / dt_s / 1e12, 2), "unit": "TFLOP/s",
        "seq": s, "batch": b, "heads": h, "head_dim": d,
        "step_ms": round(dt_s * 1e3, 2),
        "mfu": round(total / dt_s / peak, 4) if peak else None,
        "backend": jax.default_backend()}


def run_oracle_bench(batch=1, heads=8, head_dim=128, seq=16384, steps=10):
    """Same train-step timing through jax.experimental.pallas.ops.tpu
    splash attention — the mature upstream TPU kernel, benchmarked as the
    ceiling our kernel is chasing (TPU only; raises elsewhere)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as splash,
        splash_attention_mask as mask_lib,
    )

    if jax.default_backend() != "tpu":
        raise RuntimeError("splash attention oracle needs a TPU")
    b, h, d, s = batch, heads, head_dim, seq
    key = jax.random.PRNGKey(0)
    # splash layout is [heads, seq, d] per batch entry (vmap over batch)
    q = jax.random.normal(key, (b, h, s, d), jnp.bfloat16) * 0.1
    k = jax.random.normal(key, (b, h, s, d), jnp.bfloat16) * 0.1
    v = jax.random.normal(key, (b, h, s, d), jnp.bfloat16) * 0.1
    mask = mask_lib.MultiHeadMask(
        [mask_lib.CausalMask((s, s)) for _ in range(h)])
    kernel = splash.make_splash_mha_single_device(mask=mask)

    def loss(q, k, v):
        o = jax.vmap(kernel)(q, k, v)
        return jnp.mean(o.astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    chain = jax.jit(lambda q, dq: q + 0 * dq)
    g = step(q, k, v)
    q = chain(q, g[0])
    np.asarray(q[0, 0, 0, 0])
    t0 = time.time()
    for _ in range(steps):
        g = step(q, k, v)
        q = chain(q, g[0])
    np.asarray(q[0, 0, 0, 0])
    dt_s = (time.time() - t0) / steps
    total = 3.0 * 0.5 * 4.0 * b * h * s * s * d
    return {"metric": "splash_attention_oracle_tflops",
            "value": round(total / dt_s / 1e12, 2), "unit": "TFLOP/s",
            "seq": s, "step_ms": round(dt_s * 1e3, 2),
            "mfu": round(total / dt_s / 197e12, 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--seq", type=int, default=16384)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--block-q", type=int, default=None,
                    help="pin the q block (default: 512, or the tuned "
                         "winner under --autotune)")
    ap.add_argument("--block-k", type=int, default=None,
                    help="pin the k block (default: 1024, or the tuned "
                         "winner under --autotune)")
    ap.add_argument("--autotune", action="store_true",
                    help="bench the pinned/default blocks AND the "
                         "autotuned resolution side by side (sets "
                         "MXNET_AUTOTUNE=record unless already set)")
    ap.add_argument("--oracle", action="store_true",
                    help="also time upstream splash attention (the "
                         "ceiling reference)")
    cli = ap.parse_args()
    bq = 512 if cli.block_q is None else cli.block_q
    bk = 1024 if cli.block_k is None else cli.block_k
    if cli.autotune:
        os.environ.setdefault("MXNET_AUTOTUNE", "record")
        from mxnet_tpu import autotune

        base = run_bench(batch=cli.batch, heads=cli.heads,
                         head_dim=cli.head_dim, seq=cli.seq,
                         steps=cli.steps, block_q=bq, block_k=bk)
        base["config"] = "pinned %dx%d" % (bq, bk)
        print(json.dumps(base))
        tuned = run_bench(batch=cli.batch, heads=cli.heads,
                          head_dim=cli.head_dim, seq=cli.seq,
                          steps=cli.steps, block_q=None, block_k=None)
        tuned["config"] = "autotuned"
        tuned["autotune"] = autotune.stats()
        print(json.dumps(tuned))
        delta = base["step_ms"] - tuned["step_ms"]
        print(json.dumps({
            "metric": "flash_autotune_delta_ms", "value": round(delta, 2),
            "speedup": round(base["step_ms"] / tuned["step_ms"], 3)
            if tuned["step_ms"] else None}))
        return
    print(json.dumps(run_bench(
        batch=cli.batch, heads=cli.heads, head_dim=cli.head_dim,
        seq=cli.seq, steps=cli.steps, block_q=bq, block_k=bk)))
    if cli.oracle:
        print(json.dumps(run_oracle_bench(
            batch=cli.batch, heads=cli.heads, head_dim=cli.head_dim,
            seq=cli.seq, steps=cli.steps)))


if __name__ == "__main__":
    main()

"""Admin CLI for the sparse parameter plane on a live kvstore fleet.

Subcommands:

  table-ls      connect to each server and print its sharded embedding
                tables: rows held, optimizer-state rows, bytes, and how
                many rows are misplaced (owner-by-hash != this server)
  table-verify  health check — exit nonzero if any server reports
                misplaced rows, if per-key row totals disagree with a
                --expect-rows floor, or if a server's durable snapshot
                file fails its CRC sidecar (--snapshot PREFIX, where
                server i>0 journals to PREFIX.i as in
                _init_kvstore_server_module)

Usage:
  python tools/kvstore_admin.py table-ls     --servers h1:p1,h2:p2 [--json]
  python tools/kvstore_admin.py table-verify --servers h1:p1,h2:p2 \
      [--snapshot /path/prefix] [--expect-rows N] [--json]
"""
import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _parse_servers(spec):
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, _, p = entry.rpartition(":")
        out.append((host or "127.0.0.1", int(p)))
    if not out:
        sys.exit("no servers: pass --servers host:port[,host:port...]")
    return out


def _collect(servers):
    """table_info from every server: list of (addr, info-dict | error str)."""
    from mxnet_tpu.kvstore_server import ServerClient

    out = []
    for host, port in servers:
        addr = "%s:%d" % (host, port)
        try:
            c = ServerClient(host, port)
            try:
                out.append((addr, c.table_info()))
            finally:
                c.close()
        except Exception as e:
            out.append((addr, "unreachable: %s" % e))
    return out


def cmd_table_ls(cli):
    infos = _collect(_parse_servers(cli.servers))
    if cli.json:
        print(json.dumps([{"server": a,
                           "tables": i if isinstance(i, dict) else None,
                           "error": None if isinstance(i, dict) else i}
                          for a, i in infos]))
        return 0
    for addr, info in infos:
        if not isinstance(info, dict):
            print("%s  %s" % (addr, info))
            continue
        if not info:
            print("%s  (no tables)" % addr)
            continue
        for key, t in sorted(info.items(), key=lambda kv: str(kv[0])):
            print("%s  %-24s rows=%-8d state=%-8d %9.1fKB  misplaced=%d"
                  % (addr, key, t["rows"], t["state_rows"],
                     t["bytes"] / 1024.0, t["misplaced"]))
    return 0


def cmd_table_verify(cli):
    infos = _collect(_parse_servers(cli.servers))
    problems = []
    totals = {}
    for addr, info in infos:
        if not isinstance(info, dict):
            problems.append("%s: %s" % (addr, info))
            continue
        for key, t in info.items():
            if t["misplaced"]:
                problems.append("%s: key %r holds %d misplaced rows"
                                % (addr, key, t["misplaced"]))
            totals[key] = totals.get(key, 0) + t["rows"]
    if cli.expect_rows is not None:
        for key, n in sorted(totals.items(), key=str):
            if n < cli.expect_rows:
                problems.append("key %r: %d rows total < expected %d"
                                % (key, n, cli.expect_rows))
    snap_checks = []
    if cli.snapshot:
        from mxnet_tpu.filesystem import verify_crc_sidecar

        for i in range(len(_parse_servers(cli.servers))):
            path = cli.snapshot if i == 0 else "%s.%d" % (cli.snapshot, i)
            ok = verify_crc_sidecar(path)
            snap_checks.append({"path": path, "crc_ok": ok})
            if ok is False:
                problems.append("snapshot %s fails its CRC sidecar" % path)

    report = {
        "servers": [{"server": a,
                     "tables": i if isinstance(i, dict) else None,
                     "error": None if isinstance(i, dict) else i}
                    for a, i in infos],
        "row_totals": {str(k): v for k, v in totals.items()},
        "snapshots": snap_checks,
        "problems": problems,
        "ok": not problems,
    }
    if cli.json:
        print(json.dumps(report))
    else:
        for key, n in sorted(totals.items(), key=str):
            print("key %-24s total rows %d" % (key, n))
        for s in snap_checks:
            state = {True: "crc ok", False: "CRC MISMATCH",
                     None: "no sidecar"}[s["crc_ok"]]
            print("snapshot %s  %s" % (s["path"], state))
        for p in problems:
            print("PROBLEM: %s" % p)
        print("verify: %s" % ("ok" if not problems else
                              "%d problem(s)" % len(problems)))
    return 1 if problems else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    ls = sub.add_parser("table-ls", help="list sharded tables per server")
    ls.add_argument("--servers", required=True,
                    help="comma-separated host:port list")
    ls.add_argument("--json", action="store_true")
    ls.set_defaults(fn=cmd_table_ls)

    ver = sub.add_parser("table-verify",
                         help="placement + snapshot CRC health check")
    ver.add_argument("--servers", required=True)
    ver.add_argument("--snapshot", default=None,
                     help="snapshot path prefix (server i>0 uses PREFIX.i)")
    ver.add_argument("--expect-rows", type=int, default=None,
                     help="fail if any key's fleet-wide row total is below")
    ver.add_argument("--json", action="store_true")
    ver.set_defaults(fn=cmd_table_verify)

    cli = ap.parse_args(argv)
    return cli.fn(cli)


if __name__ == "__main__":
    sys.exit(main())

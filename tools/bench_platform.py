"""Multi-model platform benchmark: diurnal paging over one device pool.

One process drives the whole platform lifecycle on CPU: N tiny models
register on a pool with capacity for N/2, demand sweeps between the two
halves for a few diurnal cycles, and every cycle pages the cold half
out (writing AOT bundles) and faults the hot half in (warming from
them).  A flooding tenant runs against the last cycle to measure
per-tenant shedding isolation.

Reported (ONE json line on stdout):

* ``cold_fault_in_ms`` / ``warm_fault_in_ms`` — time from fault_in()
  start to a routable warm server, first-ever (compiles) vs
  bundle-backed (deserializes); ``warm_speedup`` is the ratio.
* ``fault_ins`` / ``page_outs`` — actuation counts over the run.
* ``warm_cold_bucket_runs`` — cold-bucket executions across every
  bundle-backed fault-in (acceptance: 0).
* ``tenant_p99_ms`` — per-tenant request p99 across the diurnal load.
* ``noisy_shed`` / ``good_shed`` — admission rejections for the
  flooding tenant vs its neighbours (acceptance: good_shed == 0).

Usage: python tools/bench_platform.py [--models 6] [--cycles 3]
       [--requests 40]
"""
import argparse
import json
import os
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentile(xs, q):
    import numpy as np

    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs \
        else None


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", type=int, default=6,
                    help="catalog size; the pool fits half of them")
    ap.add_argument("--cycles", type=int, default=3,
                    help="diurnal demand swings between the two halves")
    ap.add_argument("--requests", type=int, default=40,
                    help="requests per resident model per cycle")
    cli = ap.parse_args(argv)

    tmp = tempfile.mkdtemp(prefix="mxtpu-bench-platform-")
    os.environ["MXNET_COMPILE_CACHE_DIR"] = os.path.join(tmp, "cache")
    os.environ["MXNET_PLATFORM_MIN_RESIDENT_S"] = "0"

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.platform import (DevicePool, FrontDoor, ModelManager,
                                    ModelSpec, TenantQuotaExceededError)

    in_dim, hid = 8, 4
    n = max(2, cli.models)
    half = n // 2
    tenants = ["acme", "blue", "good"]

    rng = np.random.RandomState(7)
    specs = []
    for i in range(n):
        # distinct hidden width per model: each one is a distinct XLA
        # program, so a first fault-in genuinely compiles instead of
        # riding a neighbour's cache entry
        width = hid + i
        net = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                    num_hidden=width, name="fc")
        prefix = os.path.join(tmp, "m%d" % i)
        params = {"fc_weight": mx.nd.array(
                      rng.randn(width, in_dim).astype(np.float32)),
                  "fc_bias": mx.nd.array(rng.randn(width)
                                         .astype(np.float32))}
        mx.model.save_checkpoint(prefix, 1, net, params, {})
        specs.append(ModelSpec(
            "m%d" % i, prefix, 1, {"data": (1, in_dim)},
            tenant=tenants[i % len(tenants)], param_bytes=1000,
            server_kwargs={"buckets": (1,), "max_wait_us": 500}))

    # 20% headroom over the declared footprints: the live cost-analysis
    # refinement nudges totals a little after first contact, and the
    # pool must keep fitting `half` models (but never half+1)
    total = specs[0].footprint()["total"]
    pool = DevicePool(num_devices=1,
                      bytes_per_device=int(half * total * 1.2))
    lat_by_tenant = {}
    cold_ms, warm_ms, warm_cold_runs = [], [], 0
    x = np.zeros(in_dim, np.float32)

    with ModelManager(pool) as mgr, FrontDoor(mgr) as door:
        for s in specs:
            mgr.register_model(s)

        halves = [[s.name for s in specs[:half]],
                  [s.name for s in specs[half:half * 2]]]
        for cycle in range(cli.cycles):
            hot = halves[cycle % 2]
            for name in mgr.models():
                d = mgr.demand()[name]
                mgr.record_demand(name, (10.0 if name in hot else 0.0) - d)
            mgr.replan()
            for name in hot:
                ms = mgr.fault_in_latency_ms(name)
                if ms is None:
                    continue
                if cycle < 2:  # first visit of each half compiles
                    cold_ms.append(ms)
                else:
                    warm_ms.append(ms)
                    warm_cold_runs += \
                        mgr.server_for(name).cold_bucket_runs()
            for k in range(cli.requests):
                name = hot[k % len(hot)]
                tenant = mgr.spec(name).tenant
                t0 = time.perf_counter()
                door.predict(name, tenant=tenant, data=x)
                lat_by_tenant.setdefault(tenant, []).append(
                    (time.perf_counter() - t0) * 1e3)

        # tenant flood against the final resident set: 'noisy' must be
        # shed at the door while its neighbours' requests all land
        door.quotas.set_quota("noisy", rate=50.0, burst=5.0)
        victim = halves[(cli.cycles - 1) % 2][0]
        noisy_shed = good_before_sheds = 0
        t_end = time.monotonic() + 1.0
        while time.monotonic() < t_end:
            try:
                door.predict(victim, tenant="noisy", data=x)
            except TenantQuotaExceededError:
                noisy_shed += 1
            try:
                t0 = time.perf_counter()
                door.predict(victim, tenant="good", data=x)
                lat_by_tenant.setdefault("good", []).append(
                    (time.perf_counter() - t0) * 1e3)
            except TenantQuotaExceededError:
                good_before_sheds += 1

        snap = door.quotas.snapshot()
        fault_ins = page_outs = 0
        from mxnet_tpu import telemetry

        for line in telemetry.render_prometheus().splitlines():
            if line.startswith("mxtpu_platform_fault_ins_total{"):
                fault_ins += int(float(line.rsplit(None, 1)[1]))
            elif line.startswith("mxtpu_platform_page_outs_total{"):
                page_outs += int(float(line.rsplit(None, 1)[1]))

    rec = {
        "metric": "platform_warm_fault_in_ms",
        "value": round(_percentile(warm_ms, 50) or 0.0, 2),
        "unit": "ms",
        "models": n,
        "capacity_models": half,
        "cycles": cli.cycles,
        "cold_fault_in_ms": round(_percentile(cold_ms, 50) or 0.0, 2),
        "warm_fault_in_ms": round(_percentile(warm_ms, 50) or 0.0, 2),
        "warm_speedup": round(
            _percentile(cold_ms, 50) / _percentile(warm_ms, 50), 2)
        if cold_ms and warm_ms else None,
        "fault_ins": fault_ins,
        "page_outs": page_outs,
        "warm_cold_bucket_runs": warm_cold_runs,
        "tenant_p99_ms": {t: round(_percentile(v, 99), 2)
                          for t, v in sorted(lat_by_tenant.items())},
        "noisy_shed": noisy_shed,
        "good_shed": snap.get("good", {}).get("shed", 0),
    }
    shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(rec))
    return rec


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""im2rec — pack an image directory / list file into RecordIO
(reference: /root/reference/tools/im2rec.py and tools/im2rec.cc; same .lst
tab format ``index\\tlabel[...]\\trelpath`` and .rec/.idx output, so packs
are interchangeable with the reference's).

Usage:
  python tools/im2rec.py --list prefix root     # generate prefix.lst
  python tools/im2rec.py prefix root            # pack prefix.lst -> .rec/.idx
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu import recordio  # noqa: E402
from mxnet_tpu import image_backend  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive=False):
    i = 0
    if recursive:
        cat = {}
        for path, _, files in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(files):
                if os.path.splitext(fname)[1].lower() in EXTS:
                    fpath = os.path.join(path, fname)
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
    else:
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in EXTS:
                yield (i, fname, 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for idx, relpath, label in image_list:
            fout.write("%d\t%f\t%s\n" % (idx, float(label), relpath))


def make_list(args):
    image_list = list(list_images(args.root, args.recursive))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    write_list(args.prefix + ".lst", image_list)
    print("wrote %d entries to %s.lst" % (len(image_list), args.prefix))


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield (int(parts[0]), parts[-1],
                   [float(x) for x in parts[1:-1]])


def pack(args):
    lst = args.prefix + ".lst"
    if not os.path.exists(lst):
        raise SystemExit("list file %s not found; run --list first" % lst)
    rec = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                     args.prefix + ".rec", "w")
    n = 0
    for idx, relpath, labels in read_list(lst):
        fpath = os.path.join(args.root, relpath)
        with open(fpath, "rb") as fin:
            buf = fin.read()
        if args.resize or args.center_crop or not args.pass_through:
            img = image_backend.decode_image(buf)
            if args.resize:
                h, w = img.shape[:2]
                if h > w:
                    nw, nh = args.resize, int(h * args.resize / w)
                else:
                    nw, nh = int(w * args.resize / h), args.resize
                img = image_backend.resize_image(img, nw, nh)
            if args.center_crop:
                h, w = img.shape[:2]
                s = min(h, w)
                y0, x0 = (h - s) // 2, (w - s) // 2
                img = img[y0:y0 + s, x0:x0 + s]
            buf = image_backend.encode_image(img, args.encoding,
                                             quality=args.quality)
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, buf))
        n += 1
    rec.close()
    print("packed %d images into %s.rec" % (n, args.prefix))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst instead of packing")
    ap.add_argument("--recursive", action="store_true",
                    help="recurse into subdirs; one label per subdir")
    ap.add_argument("--shuffle", action="store_true", default=True)
    ap.add_argument("--no-shuffle", dest="shuffle", action="store_false")
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter edge to this")
    ap.add_argument("--center-crop", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    ap.add_argument("--pass-through", action="store_true",
                    help="pack raw bytes without re-encoding")
    args = ap.parse_args()
    if args.list:
        make_list(args)
    else:
        pack(args)


if __name__ == "__main__":
    main()

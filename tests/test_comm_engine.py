"""Async dependency-scheduled kvstore comms (comm_engine.py): engine
ordering contracts, implicit read completion, gradient bucketing, fp16
wire compression, and the pipelined client's exactly-once guarantee
(reference analogue: the ThreadedEngine Push/WaitForVar/WaitToRead
contract scoped to kvstore traffic)."""

import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, nd
from mxnet_tpu import kvstore_server as kvs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.comm_engine import (AsyncKVStore, CommEngine, make_async,
                                   maybe_async)
from mxnet_tpu.test_utils import assert_almost_equal


# ---------------------------------------------------------------------------
# CommEngine: dependency tracking + priority
# ---------------------------------------------------------------------------
def test_engine_priority_ordering():
    """Among READY ops the highest priority runs first (Module pushes
    front layers with the highest priority so their pulls land first)."""
    eng = CommEngine(num_threads=1)
    try:
        order = []
        gate = threading.Event()
        eng.submit(lambda: gate.wait(5), ["gate"])  # parks the one worker
        eng.submit(lambda: order.append("low"), ["a"], priority=-5)
        eng.submit(lambda: order.append("mid"), ["b"], priority=0)
        eng.submit(lambda: order.append("high"), ["c"], priority=9)
        gate.set()
        eng.wait_all()
        assert order == ["high", "mid", "low"]
    finally:
        eng.shutdown()


def test_engine_per_key_fifo_beats_priority():
    """Ops on ONE key run in submission order no matter the priorities:
    a later high-priority push must not overtake an earlier one."""
    eng = CommEngine(num_threads=4)
    try:
        order = []
        for i in range(30):
            eng.submit(lambda i=i: order.append(i), ["k"], priority=i % 7)
        eng.wait_all()
        assert order == list(range(30))
    finally:
        eng.shutdown()


def test_engine_wait_scoped_to_keys():
    eng = CommEngine(num_threads=2)
    try:
        gate = threading.Event()
        done = []
        eng.submit(lambda: (gate.wait(5), done.append("slow")), ["s"])
        eng.submit(lambda: done.append("fast"), ["f"])
        eng.wait(["f"])  # must NOT require the parked op to finish
        assert "fast" in done
        gate.set()
        eng.wait_all()
        assert done == ["fast", "slow"]
    finally:
        eng.shutdown()


def test_engine_failure_raises_at_barrier_then_recovers():
    eng = CommEngine(num_threads=2)
    try:
        def boom():
            raise ValueError("kaput")
        eng.submit(boom, ["x"], label="comm.test")
        with pytest.raises(MXNetError, match="kaput"):
            eng.wait_all()
        eng.submit(lambda: None, ["x"])
        eng.wait_all()  # engine stays usable after a surfaced failure
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# AsyncKVStore: implicit completion + env gate
# ---------------------------------------------------------------------------
def test_read_guard_resolves_pending_pull(monkeypatch):
    """Reading a pulled-into NDArray blocks until the pull lands (the
    WaitToRead contract) — no explicit kv.wait() needed."""
    kv = make_async(mx.kv.create("local"), num_threads=2, bucket_bytes=0)
    try:
        kv.init(3, nd.ones((4,)) * 5)
        inner, orig = kv.inner, kv.inner.pull

        def slow_pull(*a, **kw):
            time.sleep(0.2)  # guarantees the read happens mid-flight
            return orig(*a, **kw)

        monkeypatch.setattr(inner, "pull", slow_pull)
        out = nd.zeros((4,))
        kv.pull(3, out)
        assert_almost_equal(out, np.full(4, 5.0))  # asnumpy -> guard
        stats = kv.comm_stats()
        assert stats["pulls"] == 1
        assert stats["wait_calls"] >= 1
    finally:
        kv.close()


def test_maybe_async_env_gate(monkeypatch):
    kv = mx.kv.create("local")
    monkeypatch.setenv("MXNET_KVSTORE_ASYNC", "0")
    assert maybe_async(kv) is kv
    monkeypatch.setenv("MXNET_KVSTORE_ASYNC", "1")
    wrapped = maybe_async(kv)
    try:
        assert isinstance(wrapped, AsyncKVStore)
        assert maybe_async(wrapped) is wrapped  # idempotent
        assert maybe_async(None) is None
    finally:
        wrapped.close()


# ---------------------------------------------------------------------------
# async vs sync training: bit-identical weights
# ---------------------------------------------------------------------------
def _mlp(k=3):
    from mxnet_tpu import sym

    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=k, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _train_weights(monkeypatch, async_on):
    monkeypatch.setenv("MXNET_KVSTORE_ASYNC", "1" if async_on else "0")
    rng = np.random.RandomState(11)
    X = rng.randn(120, 10).astype(np.float32)
    y = (rng.randn(120) > 0).astype(np.float32)
    mx.random.seed(7)  # identical Xavier draws across the two runs
    train = mx.io.NDArrayIter(X, y, batch_size=30, shuffle=False)
    mod = mx.mod.Module(_mlp(2), label_names=("softmax_label",))
    # a KVStore INSTANCE keeps update_on_kvstore=True, so the update
    # path really goes push -> server updater -> pull
    mod.fit(train, num_epoch=3, kvstore=mx.kv.create("local"),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier())
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in sorted(args.items())}


def test_async_training_bit_identical_to_sync(monkeypatch):
    """The engine only reorders INDEPENDENT keys; per-key FIFO plus the
    forward() barrier make the async schedule numerically invisible."""
    sync_w = _train_weights(monkeypatch, async_on=False)
    async_w = _train_weights(monkeypatch, async_on=True)
    assert sync_w.keys() == async_w.keys()
    for name in sync_w:
        assert np.array_equal(sync_w[name], async_w[name]), \
            "weights diverged for %s" % name


def test_module_backward_param_order():
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    n = len(mod._exec_group.param_names)
    assert mod._exec_group.backward_param_order() == \
        list(range(n - 1, -1, -1))


# ---------------------------------------------------------------------------
# gradient bucketing over dist_async
# ---------------------------------------------------------------------------
def test_bucketed_push_pull_values_and_metrics():
    kv = make_async(mx.kv.create("dist_async"), num_threads=4,
                    bucket_bytes=1 << 16)
    try:
        n = 40
        for i in range(n):
            kv.init(i, nd.zeros((8,)))
        for i in range(n):
            kv.push(i, nd.array(np.full(8, float(i), np.float32)))
        outs = [nd.zeros((8,)) for _ in range(n)]
        for i in range(n):
            kv.pull(i, outs[i])
        kv.wait_all()
        for i in range(n):
            assert_almost_equal(outs[i], np.full(8, float(i)))
        stats = kv.comm_stats()
        assert stats["pushes"] == n and stats["pulls"] == n
        assert stats["bucket_flushes"] >= 2  # >=1 push + >=1 pull bucket
        assert stats["bucket_keys"] >= 2 * n - 2
        assert 0.0 < stats["bucket_fill_ratio"] <= 1.0
        assert stats["bytes_pushed"] == n * 8 * 4
        assert stats["bytes_pulled"] == n * 8 * 4
        assert stats["queue_depth"] == 0
        assert stats["inflight_peak"] >= 1
    finally:
        kv.close()


def test_bucket_cross_op_same_key_ordering():
    """push(k); pull(k) with both buffered: the pull must observe the
    push (opposing buffer flushes keep per-key program order)."""
    kv = make_async(mx.kv.create("dist_async"), num_threads=4,
                    bucket_bytes=1 << 20)  # nothing flushes on bytes
    try:
        kv.init(0, nd.zeros((4,)))
        out = nd.zeros((4,))
        kv.push(0, nd.ones((4,)) * 3)
        kv.pull(0, out)
        kv.wait_all()
        assert_almost_equal(out, np.full(4, 3.0))
    finally:
        kv.close()


def test_push_multi_pull_multi_direct():
    kv = mx.kv.create("dist_async")
    try:
        shapes = [(3,), (2, 4), (5,)]
        for i, s in enumerate(shapes):
            kv.init(i, nd.zeros(s))
        kv.push_multi(
            [(i, [nd.array(np.full(s, i + 1.0, np.float32))])
             for i, s in enumerate(shapes)])
        outs = [nd.zeros(s) for s in shapes]
        kv.pull_multi([(i, [outs[i]]) for i in range(len(shapes))])
        for i, s in enumerate(shapes):
            assert_almost_equal(outs[i], np.full(s, i + 1.0))
    finally:
        kv.close()


def test_dist_push_merges_multi_device_values_on_device():
    """One push of a list of per-device grads transfers ONE merged array
    (the old path round-tripped every value through asnumpy first)."""
    kv = mx.kv.create("dist_async")
    try:
        kv.init(1, nd.zeros((4, 4)))
        kv.push(1, [nd.ones((4, 4)), nd.ones((4, 4)) * 2])
        out = nd.zeros((4, 4))
        kv.pull(1, out)
        assert_almost_equal(out, np.full((4, 4), 3.0))
    finally:
        kv.close()


def test_fp16_compression_error_feedback(monkeypatch):
    """fp16-on-the-wire with per-key error feedback: the second push
    carries the first push's quantization residual, bit-exactly."""
    monkeypatch.setenv("MXNET_KVSTORE_COMPRESS", "fp16")
    kv = mx.kv.create("dist_async")
    try:
        rng = np.random.RandomState(3)
        v1 = rng.randn(64).astype(np.float32)
        v2 = rng.randn(64).astype(np.float32)
        kv.init(9, nd.zeros((64,)))
        kv.push(9, nd.array(v1))
        kv.push(9, nd.array(v2))
        out = nd.zeros((64,))
        kv.pull(9, out)
        s1 = v1.astype(np.float16)
        r1 = v1 - s1.astype(np.float32)
        s2 = (v2 + r1).astype(np.float16)
        # no updater: the server accumulates the decompressed pushes
        expect = s1.astype(np.float32) + s2.astype(np.float32)
        assert np.array_equal(out.asnumpy(), expect)
    finally:
        kv.close()


# ---------------------------------------------------------------------------
# pipelined transport under fault injection
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_pipelined_client_two_inflight_exactly_once(monkeypatch):
    """TWO pushes in flight when an ACK is dropped: the reconnect replays
    both envelopes under their original tokens and the server applies
    each exactly once."""
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "40")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "20")
    srv = kvs.start_server(num_workers=1)
    host, port = srv.addr
    try:
        # recv #1 is the init ACK; #2 is the first push ACK — dropped
        # after the server already applied it, with push #2 also in flight
        with faults.inject("kv.client.recv:drop=1@#2"):
            with kvs.ServerClient(host, port) as c:
                c.init(0, np.zeros(4, np.float32))
                e1 = c._submit(("push", 0, np.full(4, 5.0, np.float32), 0))
                e2 = c._submit(("push", 0, np.full(4, 7.0, np.float32), 0))
                assert e1["event"].wait(10) and e2["event"].wait(10)
                assert e1["exc"] is None and e2["exc"] is None
                out = c.pull(0)
        np.testing.assert_array_equal(out, np.full(4, 12.0, np.float32))
        assert srv.applied_pushes == 2  # the replay was deduplicated
    finally:
        srv.stop()


@pytest.mark.chaos
def test_bucketed_push_survives_socket_loss(monkeypatch):
    """A whole bucket rides one idempotency token: socket loss mid-stream
    replays the fused envelope and every inner push applies once."""
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "40")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "20")
    with faults.inject("kv.client.recv:drop=1@#4"):
        kv = make_async(mx.kv.create("dist_async"), num_threads=2,
                        bucket_bytes=1 << 16)
        try:
            n = 20
            for i in range(n):
                kv.init(i, nd.zeros((8,)))
            for i in range(n):
                kv.push(i, nd.array(np.full(8, float(i), np.float32)))
            outs = [nd.zeros((8,)) for _ in range(n)]
            for i in range(n):
                kv.pull(i, outs[i])
            kv.wait_all()
            for i in range(n):
                assert_almost_equal(outs[i], np.full(8, float(i)))
            assert kv.inner._server.applied_pushes == n
        finally:
            kv.close()


# ---------------------------------------------------------------------------
# PrefetchingIter lifecycle
# ---------------------------------------------------------------------------
def test_prefetching_iter_close_and_context_manager():
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = mx.io.NDArrayIter(X, np.zeros(12, np.float32), batch_size=4)
    with mx.io.PrefetchingIter(base) as it:
        assert len(list(it)) == 3
    it.close()  # idempotent
    it.reset()  # and restartable
    batches = list(it)
    assert len(batches) == 3
    assert_almost_equal(batches[0].data[0], X[:4])
    it.close()


def test_fit_closes_prefetching_iter():
    rng = np.random.RandomState(0)
    X = rng.randn(60, 10).astype(np.float32)
    y = (rng.randn(60) > 0).astype(np.float32)
    base = mx.io.NDArrayIter(X, y, batch_size=20)
    it = mx.io.PrefetchingIter(base)
    mod = mx.mod.Module(_mlp(2), label_names=("softmax_label",))
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    assert it._exhausted  # fit's finally tore the workers down

"""Distributed Module.fit smoke across 4 workers (reference:
tests/nightly/dist_lenet.py) — each worker trains on its data shard through
kvstore='dist_sync'; asserts the final parameters are bitwise identical on
every worker and that training reduced the loss."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def main():
    kv = mx.kvstore.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers

    rng = np.random.RandomState(7)  # same data everywhere; shard by rank
    X = rng.randn(256, 16).astype(np.float32)
    w = rng.randn(16)
    y = (X @ w > 0).astype(np.float32)
    shard = slice(rank * 256 // nworker, (rank + 1) * 256 // nworker)
    train = mx.io.NDArrayIter(X[shard], y[shard], batch_size=16)

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu())
    mx.random.seed(0)
    np.random.seed(0)
    mod.fit(train, num_epoch=10, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Accuracy())

    args, _ = mod.get_params()
    flat = np.concatenate([args[k].asnumpy().ravel() for k in sorted(args)])
    from jax.experimental import multihost_utils

    gathered = np.asarray(multihost_utils.process_allgather(
        jax.numpy.asarray(flat)))
    for r in range(nworker):
        np.testing.assert_array_equal(gathered[r], gathered[0])

    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=16),
                      mx.metric.Accuracy())
    acc = score[0][1]
    assert acc > 0.8, "dist training did not converge: acc=%s" % acc
    print("dist_train_worker %d/%d OK acc=%.3f" % (rank, nworker, acc),
          flush=True)


if __name__ == "__main__":
    main()

"""Executor bind/forward/backward semantics: grad_req, aux updates, reshape,
monitor (reference: tests/python/unittest/test_executor.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def test_bind_forward_backward():
    a, b = sym.Variable("a"), sym.Variable("b")
    s = a * b
    a_np = np.random.randn(3, 3).astype(np.float32)
    b_np = np.random.randn(3, 3).astype(np.float32)
    exe = s.bind(mx.cpu(), {"a": nd.array(a_np), "b": nd.array(b_np)},
                 args_grad={"a": nd.zeros((3, 3)), "b": nd.zeros((3, 3))})
    out = exe.forward(is_train=True)[0]
    assert_almost_equal(out, a_np * b_np)
    exe.backward([nd.ones((3, 3))])
    assert_almost_equal(exe.grad_dict["a"], b_np)
    assert_almost_equal(exe.grad_dict["b"], a_np)


def test_grad_req_null():
    a, b = sym.Variable("a"), sym.Variable("b")
    s = a * b
    exe = s.bind(mx.cpu(), {"a": nd.ones((2,)), "b": nd.ones((2,))},
                 args_grad={"a": nd.zeros((2,))},
                 grad_req={"a": "write", "b": "null"})
    exe.forward(is_train=True)
    exe.backward([nd.ones((2,))])
    assert_almost_equal(exe.grad_dict["a"], np.ones(2, np.float32))
    assert exe.grad_dict.get("b") is None


def test_grad_req_add_accumulates():
    a = sym.Variable("a")
    s = a * 3.0
    exe = s.bind(mx.cpu(), {"a": nd.ones((2,))},
                 args_grad={"a": nd.zeros((2,))}, grad_req="add")
    for i in range(3):
        exe.forward(is_train=True)
        exe.backward([nd.ones((2,))])
    assert_almost_equal(exe.grad_dict["a"], np.full(2, 9.0, np.float32))


def test_grad_req_write_overwrites():
    a = sym.Variable("a")
    s = a * 3.0
    exe = s.bind(mx.cpu(), {"a": nd.ones((2,))},
                 args_grad={"a": nd.zeros((2,))}, grad_req="write")
    for _ in range(3):
        exe.forward(is_train=True)
        exe.backward([nd.ones((2,))])
    assert_almost_equal(exe.grad_dict["a"], np.full(2, 3.0, np.float32))


def test_simple_bind():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=4, name="fc")
    exe = net.simple_bind(mx.cpu(), data=(2, 6))
    assert exe.arg_dict["fc_weight"].shape == (4, 6)
    exe.arg_dict["data"][:] = 1.0
    out = exe.forward()[0]
    assert out.shape == (2, 4)


def test_aux_state_update_only_in_train():
    data = sym.Variable("data")
    s = sym.BatchNorm(data=data, momentum=0.5, name="bn")
    x = np.random.randn(8, 3).astype(np.float32) * 2 + 1
    exe = s.bind(mx.cpu(), {"data": nd.array(x), "bn_gamma": nd.ones((3,)),
                            "bn_beta": nd.zeros((3,))},
                 aux_states={"bn_moving_mean": nd.zeros((3,)),
                             "bn_moving_var": nd.ones((3,))})
    exe.forward(is_train=False)
    assert_almost_equal(exe.aux_dict["bn_moving_mean"], np.zeros(3))
    exe.forward(is_train=True)
    mm = exe.aux_dict["bn_moving_mean"].asnumpy()
    assert np.abs(mm).sum() > 0  # updated by momentum rule


def test_outputs_dict_and_multiple_outputs():
    a = sym.Variable("a")
    g = sym.Group([a + 1.0, a * 2.0])
    exe = g.bind(mx.cpu(), {"a": nd.array([1.0, 2.0])})
    outs = exe.forward()
    assert len(outs) == 2
    assert_almost_equal(outs[0], [2.0, 3.0])
    assert_almost_equal(outs[1], [2.0, 4.0])


def test_monitor_callback():
    seen = []
    a = sym.Variable("a")
    s = sym.Activation(data=a * 2.0, act_type="relu", name="act")
    exe = s.bind(mx.cpu(), {"a": nd.array([1.0, -1.0])})
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=True)
    assert len(seen) > 0


def test_reshape():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=4, name="fc")
    exe = net.simple_bind(mx.cpu(), data=(2, 6))
    exe2 = exe.reshape(data=(5, 6))
    exe2.arg_dict["data"][:] = 1.0
    assert exe2.forward()[0].shape == (5, 4)
    # weights shared with original executor
    assert exe2.arg_dict["fc_weight"] is exe.arg_dict["fc_weight"]


def test_copy_params_from():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=2, name="fc")
    exe = net.simple_bind(mx.cpu(), data=(1, 3))
    w = np.random.randn(2, 3).astype(np.float32)
    exe.copy_params_from({"fc_weight": nd.array(w), "fc_bias": nd.zeros((2,))})
    assert_almost_equal(exe.arg_dict["fc_weight"], w)


def test_head_gradient_scaling():
    a = sym.Variable("a")
    s = a * 1.0
    exe = s.bind(mx.cpu(), {"a": nd.ones((3,))},
                 args_grad={"a": nd.zeros((3,))})
    exe.forward(is_train=True)
    exe.backward([nd.array([1.0, 2.0, 3.0])])
    assert_almost_equal(exe.grad_dict["a"], [1.0, 2.0, 3.0])

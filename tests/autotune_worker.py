"""Subprocess side of the cross-process autotune acceptance test.

Resolves the flash-attention blocks for the benched shape family
(seq 512, head_dim 128, f32, causal) with blocks UNPINNED, then prints
one JSON line with the effective blocks, the autotune counters, and the
compile-cache key fingerprint.  The parent process drives it twice
against one MXNET_AUTOTUNE_DIR: first in record mode (pays the tuning
cost), then in a fresh process in lookup mode (must inherit the winner
with ZERO re-tuning — the once-per-fleet contract).
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from mxnet_tpu import autotune
    from mxnet_tpu.ops.attention import resolve_blocks

    bq, bk = resolve_blocks(None, None, 512, 512, head_dim=128,
                            dtype=np.dtype("float32"), causal=True)
    print(json.dumps({"blocks": [bq, bk], "stats": autotune.stats(),
                      "fingerprint": autotune.cache_fingerprint()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Op math vs numpy + numeric-gradient checks per op family
(reference: tests/python/unittest/test_operator.py + test_utils harness)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import (
    assert_almost_equal, check_consistency, check_numeric_gradient,
    check_symbolic_backward, check_symbolic_forward)


# ---------------------------------------------------------------------------
# elemwise family
# ---------------------------------------------------------------------------

def test_unary_forward():
    x = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    for name, ref in [("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
                      ("square", np.square), ("abs", np.abs),
                      ("sign", np.sign), ("floor", np.floor),
                      ("ceil", np.ceil), ("tanh", np.tanh),
                      ("sin", np.sin), ("cos", np.cos)]:
        data = sym.Variable("data")
        s = getattr(sym, name)(data=data)
        check_symbolic_forward(s, {"data": x}, [ref(x)], rtol=1e-4, atol=1e-5)


def test_unary_gradient():
    x = np.random.uniform(0.5, 1.5, (2, 3)).astype(np.float64)
    for name in ["exp", "log", "sqrt", "tanh", "sigmoid"]:
        data = sym.Variable("data")
        s = getattr(sym, name)(data=data)
        check_numeric_gradient(s, {"data": x})


def test_binary_broadcast_gradient():
    a = np.random.uniform(0.5, 1.5, (2, 3)).astype(np.float64)
    b = np.random.uniform(0.5, 1.5, (1, 3)).astype(np.float64)
    for op in ["broadcast_add", "broadcast_mul", "broadcast_sub",
               "broadcast_div"]:
        lhs, rhs = sym.Variable("lhs"), sym.Variable("rhs")
        s = getattr(sym, op)(lhs=lhs, rhs=rhs)
        check_numeric_gradient(s, {"lhs": a, "rhs": b})


def test_scalar_ops():
    x = np.random.randn(3, 3).astype(np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(data + 2.0, {"data": x}, [x + 2.0])
    check_symbolic_forward(2.0 / (data + 3.0), {"data": x}, [2.0 / (x + 3.0)],
                           rtol=1e-4, atol=1e-5)
    check_symbolic_forward(data ** 2.0, {"data": x}, [x ** 2.0], rtol=1e-4,
                           atol=1e-5)


def test_smooth_l1():
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], dtype=np.float32)
    data = sym.Variable("data")
    s = sym.smooth_l1(data=data, scalar=1.0)
    expected = np.where(np.abs(x) < 1.0, 0.5 * x * x, np.abs(x) - 0.5)
    check_symbolic_forward(s, {"data": x}, [expected.astype(np.float32)])


# ---------------------------------------------------------------------------
# reduce family
# ---------------------------------------------------------------------------

def test_reduce_forward_backward():
    x = np.random.randn(2, 3, 4).astype(np.float64)
    data = sym.Variable("data")
    check_symbolic_forward(sym.sum(data=data, axis=1), {"data": x},
                           [x.sum(axis=1)], rtol=1e-5, atol=1e-5)
    check_symbolic_forward(sym.mean(data=data, axis=(0, 2)), {"data": x},
                           [x.mean(axis=(0, 2))], rtol=1e-5, atol=1e-5)
    check_numeric_gradient(sym.sum(data=data, axis=1), {"data": x})
    check_symbolic_forward(sym.sum(data=data, axis=1, keepdims=True),
                           {"data": x}, [x.sum(axis=1, keepdims=True)],
                           rtol=1e-5, atol=1e-5)


def test_argmax_argmin():
    x = np.random.randn(3, 5).astype(np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(sym.argmax(data=data, axis=1), {"data": x},
                           [x.argmax(axis=1).astype(np.float32)])
    check_symbolic_forward(sym.argmin(data=data, axis=0), {"data": x},
                           [x.argmin(axis=0).astype(np.float32)])


# ---------------------------------------------------------------------------
# matrix family
# ---------------------------------------------------------------------------

def test_dot_and_batch_dot():
    a = np.random.randn(3, 4).astype(np.float64)
    b = np.random.randn(4, 5).astype(np.float64)
    lhs, rhs = sym.Variable("lhs"), sym.Variable("rhs")
    s = sym.dot(lhs=lhs, rhs=rhs)
    check_symbolic_forward(s, {"lhs": a, "rhs": b}, [a @ b], rtol=1e-4,
                           atol=1e-4)
    check_numeric_gradient(s, {"lhs": a, "rhs": b}, rtol=2e-2, atol=2e-3)

    a3 = np.random.randn(2, 3, 4).astype(np.float32)
    b3 = np.random.randn(2, 4, 5).astype(np.float32)
    s = sym.batch_dot(lhs=lhs, rhs=rhs)
    check_symbolic_forward(s, {"lhs": a3, "rhs": b3}, [a3 @ b3], rtol=1e-4,
                           atol=1e-4)


def test_transpose_reshape_slice():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    data = sym.Variable("data")
    check_symbolic_forward(sym.transpose(data=data, axes=(2, 0, 1)),
                           {"data": x}, [x.transpose(2, 0, 1)])
    check_symbolic_forward(sym.Reshape(data=data, shape=(6, 4)), {"data": x},
                           [x.reshape(6, 4)])
    check_symbolic_forward(sym.slice_axis(data=data, axis=1, begin=0, end=2),
                           {"data": x}, [x[:, 0:2]])
    check_symbolic_forward(sym.Flatten(data=data), {"data": x},
                           [x.reshape(2, 12)])


def test_clip_where_tile_repeat():
    x = np.random.randn(3, 4).astype(np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(sym.clip(data=data, a_min=-0.5, a_max=0.5),
                           {"data": x}, [np.clip(x, -0.5, 0.5)])
    check_symbolic_forward(sym.tile(data=data, reps=(2, 1)), {"data": x},
                           [np.tile(x, (2, 1))])
    check_symbolic_forward(sym.repeat(data=data, repeats=2, axis=1),
                           {"data": x}, [np.repeat(x, 2, axis=1)])


def test_swapaxis_expanddims():
    x = np.random.randn(2, 3, 4).astype(np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(sym.SwapAxis(data=data, dim1=0, dim2=2),
                           {"data": x}, [np.swapaxes(x, 0, 2)])
    check_symbolic_forward(sym.expand_dims(data=data, axis=1), {"data": x},
                           [np.expand_dims(x, 1)])


# ---------------------------------------------------------------------------
# indexing family
# ---------------------------------------------------------------------------

def test_embedding_and_take():
    weight = np.random.randn(10, 4).astype(np.float64)
    idx = np.array([1.0, 3.0, 1.0, 7.0], dtype=np.float64)
    data, w = sym.Variable("data"), sym.Variable("weight")
    s = sym.Embedding(data=data, weight=w, input_dim=10, output_dim=4)
    check_symbolic_forward(s, {"data": idx, "weight": weight},
                           [weight[idx.astype(int)]], rtol=1e-5, atol=1e-6)
    # gradient wrt weight only (indices not differentiable)
    check_numeric_gradient(s, {"data": idx, "weight": weight},
                           grad_nodes=["weight"])

    a = np.random.randn(5, 3).astype(np.float32)
    tidx = np.array([1.0, 3.0, 0.0, 4.0], dtype=np.float32)
    check_symbolic_forward(sym.take(a=sym.Variable("a"),
                                    indices=sym.Variable("indices")),
                           {"a": a, "indices": tidx},
                           [a[tidx.astype(int)]])


def test_one_hot_pick():
    idx = np.array([0.0, 2.0, 1.0], dtype=np.float32)
    data = sym.Variable("data")
    check_symbolic_forward(sym.one_hot(indices=data, depth=3), {"data": idx},
                           [np.eye(3, dtype=np.float32)[idx.astype(int)]])
    x = np.random.randn(3, 4).astype(np.float32)
    s = sym.pick(data=sym.Variable("x"), index=sym.Variable("idx"), axis=1)
    check_symbolic_forward(
        s, {"x": x, "idx": np.array([1.0, 0.0, 3.0], np.float32)},
        [x[np.arange(3), [1, 0, 3]]])


# ---------------------------------------------------------------------------
# ordering family
# ---------------------------------------------------------------------------

def test_topk_sort_argsort():
    x = np.random.randn(3, 6).astype(np.float32)
    data = sym.Variable("data")
    out = sym.topk(data=data, k=2, axis=1)
    expected = np.argsort(-x, axis=1, kind="stable")[:, :2].astype(np.float32)
    check_symbolic_forward(out, {"data": x}, [expected])
    check_symbolic_forward(sym.sort(data=data, axis=1), {"data": x},
                           [np.sort(x, axis=1)])
    check_symbolic_forward(sym.argsort(data=data, axis=1), {"data": x},
                           [np.argsort(x, axis=1, kind="stable").astype(np.float32)])


# ---------------------------------------------------------------------------
# nn family
# ---------------------------------------------------------------------------

def test_fully_connected():
    x = np.random.randn(4, 5).astype(np.float64)
    w = np.random.randn(3, 5).astype(np.float64)
    b = np.random.randn(3).astype(np.float64)
    data = sym.Variable("data")
    s = sym.FullyConnected(data=data, num_hidden=3, name="fc")
    check_symbolic_forward(s, {"data": x, "fc_weight": w, "fc_bias": b},
                           [x @ w.T + b], rtol=1e-4, atol=1e-4)
    check_numeric_gradient(s, {"data": x, "fc_weight": w, "fc_bias": b},
                           rtol=2e-2, atol=2e-3)


def test_activation():
    x = np.random.randn(3, 4).astype(np.float32)
    data = sym.Variable("data")
    for act, ref in [("relu", lambda v: np.maximum(v, 0)),
                     ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
                     ("tanh", np.tanh),
                     ("softrelu", lambda v: np.log1p(np.exp(v)))]:
        s = sym.Activation(data=data, act_type=act)
        check_symbolic_forward(s, {"data": x}, [ref(x).astype(np.float32)],
                               rtol=1e-4, atol=1e-5)


def test_convolution_vs_reference_math():
    # 1x1 conv == per-pixel matmul; exact oracle without torch
    x = np.random.randn(2, 3, 5, 5).astype(np.float32)
    w = np.random.randn(4, 3, 1, 1).astype(np.float32)
    b = np.zeros(4, np.float32)
    data = sym.Variable("data")
    s = sym.Convolution(data=data, num_filter=4, kernel=(1, 1), name="conv")
    expected = np.einsum("nchw,kc->nkhw", x, w[:, :, 0, 0])
    check_symbolic_forward(s, {"data": x, "conv_weight": w, "conv_bias": b},
                           [expected], rtol=1e-4, atol=1e-4)


def test_convolution_gradient():
    x = np.random.randn(1, 2, 4, 4).astype(np.float64)
    w = np.random.randn(2, 2, 3, 3).astype(np.float64)
    b = np.random.randn(2).astype(np.float64)
    data = sym.Variable("data")
    s = sym.Convolution(data=data, num_filter=2, kernel=(3, 3), pad=(1, 1),
                        name="conv")
    check_numeric_gradient(s, {"data": x, "conv_weight": w, "conv_bias": b},
                           rtol=3e-2, atol=4e-3)


def test_convolution_torch_oracle():
    torch = pytest.importorskip("torch")
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    w = np.random.randn(5, 3, 3, 3).astype(np.float32)
    b = np.random.randn(5).astype(np.float32)
    ref = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
        stride=2, padding=1).numpy()
    data = sym.Variable("data")
    s = sym.Convolution(data=data, num_filter=5, kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1), name="conv")
    check_symbolic_forward(s, {"data": x, "conv_weight": w, "conv_bias": b},
                           [ref], rtol=1e-3, atol=1e-3)


def test_pooling():
    torch = pytest.importorskip("torch")
    x = np.random.randn(2, 3, 6, 6).astype(np.float32)
    data = sym.Variable("data")
    for pool_type, tfn in [("max", torch.nn.functional.max_pool2d),
                           ("avg", torch.nn.functional.avg_pool2d)]:
        s = sym.Pooling(data=data, pool_type=pool_type, kernel=(2, 2),
                        stride=(2, 2))
        ref = tfn(torch.from_numpy(x), 2, 2).numpy()
        check_symbolic_forward(s, {"data": x}, [ref], rtol=1e-4, atol=1e-5)
    s = sym.Pooling(data=data, global_pool=True, pool_type="avg", kernel=(1, 1))
    check_symbolic_forward(s, {"data": x}, [x.mean(axis=(2, 3), keepdims=True)],
                           rtol=1e-4, atol=1e-5)


def test_batchnorm_forward():
    x = np.random.randn(4, 3, 5, 5).astype(np.float32)
    gamma = np.random.uniform(0.5, 1.5, 3).astype(np.float32)
    beta = np.random.randn(3).astype(np.float32)
    data = sym.Variable("data")
    s = sym.BatchNorm(data=data, eps=1e-3, fix_gamma=False, name="bn")
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    expected = ((x - mean[None, :, None, None]) /
                np.sqrt(var[None, :, None, None] + 1e-3) *
                gamma[None, :, None, None] + beta[None, :, None, None])
    exe = s.bind(mx.cpu(), {"data": nd.array(x), "bn_gamma": nd.array(gamma),
                            "bn_beta": nd.array(beta)},
                 aux_states={"bn_moving_mean": nd.zeros((3,)),
                             "bn_moving_var": nd.ones((3,))})
    out = exe.forward(is_train=True)[0]
    assert_almost_equal(out, expected, rtol=1e-3, atol=1e-3)
    # aux moving stats updated toward batch stats
    mm = exe.aux_dict["bn_moving_mean"].asnumpy()
    assert np.abs(mm).sum() > 0


def test_dropout_train_vs_test():
    x = np.ones((100, 100), np.float32)
    data = sym.Variable("data")
    s = sym.Dropout(data=data, p=0.5)
    exe = s.bind(mx.cpu(), {"data": nd.array(x)})
    out_test = exe.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out_test, x)  # identity at inference
    out_train = exe.forward(is_train=True)[0].asnumpy()
    frac_zero = (out_train == 0).mean()
    assert 0.4 < frac_zero < 0.6
    # inverted dropout: survivors scaled by 1/(1-p)
    assert_almost_equal(out_train[out_train != 0],
                        np.full((out_train != 0).sum(), 2.0, np.float32))


def test_softmax_output_and_grad():
    x = np.random.randn(4, 3).astype(np.float32)
    label = np.array([0.0, 2.0, 1.0, 1.0], np.float32)
    data = sym.Variable("data")
    s = sym.SoftmaxOutput(data=data, name="softmax")
    e = np.exp(x - x.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    exe = s.bind(mx.cpu(), {"data": nd.array(x),
                            "softmax_label": nd.array(label)},
                 args_grad={"data": nd.zeros((4, 3))})
    out = exe.forward(is_train=True)[0]
    assert_almost_equal(out, p, rtol=1e-4, atol=1e-5)
    exe.backward()
    expected_grad = p.copy()
    expected_grad[np.arange(4), label.astype(int)] -= 1.0
    assert_almost_equal(exe.grad_dict["data"], expected_grad / 1.0, rtol=1e-4,
                        atol=1e-4)


def test_regression_outputs():
    x = np.random.randn(4, 2).astype(np.float32)
    label = np.random.randn(4, 2).astype(np.float32)
    data, lab = sym.Variable("data"), sym.Variable("label")
    s = sym.LinearRegressionOutput(data=data, label=lab)
    exe = s.bind(mx.cpu(), {"data": nd.array(x), "label": nd.array(label)},
                 args_grad={"data": nd.zeros((4, 2))})
    out = exe.forward(is_train=True)[0]
    assert_almost_equal(out, x)
    exe.backward()
    # reference regression_output-inl.h:70-77: grad = (out-label)/num_output
    # where num_output = label.size/batch = 2 here
    assert_almost_equal(exe.grad_dict["data"], (x - label) / 2.0, rtol=1e-4,
                        atol=1e-5)


def test_leaky_relu():
    x = np.random.randn(3, 4).astype(np.float32)
    data = sym.Variable("data")
    s = sym.LeakyReLU(data=data, act_type="leaky", slope=0.1)
    check_symbolic_forward(s, {"data": x},
                           [np.where(x > 0, x, 0.1 * x).astype(np.float32)])


def test_concat_slicechannel():
    a = np.random.randn(2, 3).astype(np.float32)
    b = np.random.randn(2, 4).astype(np.float32)
    s = sym.Concat(sym.Variable("a"), sym.Variable("b"), dim=1)
    check_symbolic_forward(s, {"a": a, "b": b},
                           [np.concatenate([a, b], axis=1)])
    x = np.random.randn(2, 6).astype(np.float32)
    s = sym.SliceChannel(data=sym.Variable("x"), num_outputs=3, axis=1)
    check_symbolic_forward(s, {"x": x}, [x[:, 0:2], x[:, 2:4], x[:, 4:6]])


def test_sequence_ops():
    x = np.random.randn(4, 2, 3).astype(np.float32)  # (seq, batch, feat)
    length = np.array([2.0, 4.0], np.float32)
    data, lens = sym.Variable("data"), sym.Variable("len")
    s = sym.SequenceMask(data=data, sequence_length=lens,
                         use_sequence_length=True)
    expected = x.copy()
    expected[2:, 0] = 0.0
    check_symbolic_forward(s, {"data": x, "len": length}, [expected])
    s = sym.SequenceLast(data=data, sequence_length=lens,
                         use_sequence_length=True)
    check_symbolic_forward(s, {"data": x, "len": length},
                           [np.stack([x[1, 0], x[3, 1]])])


def test_block_grad_stops_gradient():
    x = np.random.randn(3, 3).astype(np.float64)
    data = sym.Variable("data")
    s = sym.BlockGrad(data=data * 2.0) + data
    exe = s.bind(mx.cpu(), {"data": nd.array(x.astype(np.float32))},
                 args_grad={"data": nd.zeros((3, 3))})
    exe.forward(is_train=True)
    exe.backward([nd.ones((3, 3))])
    assert_almost_equal(exe.grad_dict["data"], np.ones((3, 3), np.float32))


# ---------------------------------------------------------------------------
# sampling + consistency
# ---------------------------------------------------------------------------

def test_sample_ops_statistics():
    s = sym.uniform(low=0.0, high=1.0, shape=(2000,))
    exe = s.bind(mx.cpu(), {})
    out = exe.forward(is_train=True)[0].asnumpy()
    assert 0.0 <= out.min() and out.max() <= 1.0
    assert abs(out.mean() - 0.5) < 0.05
    s = sym.normal(loc=0.0, scale=1.0, shape=(2000,))
    out = s.bind(mx.cpu(), {}).forward(is_train=True)[0].asnumpy()
    assert abs(out.mean()) < 0.1 and abs(out.std() - 1.0) < 0.1


def test_bf16_consistency():
    x = np.random.randn(4, 8).astype(np.float32)
    w = np.random.randn(6, 8).astype(np.float32)
    b = np.random.randn(6).astype(np.float32)
    data = sym.Variable("data")
    s = sym.FullyConnected(data=data, num_hidden=6, name="fc")
    s = sym.Activation(data=s, act_type="tanh")
    check_consistency(s, {"data": x, "fc_weight": w, "fc_bias": b},
                      dtypes=("float32", "bfloat16"), rtol=5e-2, atol=5e-2)

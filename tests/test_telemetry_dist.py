"""Cluster-wide observability (docs/how_to/observability.md): distributed
trace propagation through kvstore RPC envelopes, the fleet metrics
aggregator, straggler detection on sync merge rounds, and the crash
flight recorder."""
import glob
import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry.distributed import FleetAggregator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry._reset_for_tests()
    yield
    telemetry._reset_for_tests()


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# trace context propagation (in-process client/server pair)
# ---------------------------------------------------------------------------
def test_rpc_trace_ids_shared_between_client_and_server_spans():
    telemetry.enable(trace=True)
    from mxnet_tpu.kvstore_server import ServerClient, start_server

    srv = start_server(port=0, num_workers=1)
    try:
        with ServerClient("127.0.0.1", srv.addr[1]) as cli:
            cli.init("w", np.zeros(4, np.float32))
            cli.push("w", np.ones(4, np.float32))
            cli.pull("w")
            cli.multi([("init", "a", np.ones(2, np.float32)),
                       ("init", "b", np.ones(2, np.float32))])
        evs = telemetry.tracer.events()
        client = {e["args"]["trace"]: e["name"] for e in evs
                  if e.get("cat") == "kvclient" and e.get("args")}
        server = {e["args"]["trace"]: e["name"] for e in evs
                  if e.get("cat") == "kvserver" and e.get("args")}
        # every client RPC span's trace id shows up on a server handler
        # span: init, push, pull, and the fused multi bucket
        assert client and set(client) <= set(server)
        assert "kv.client.multi" in client.values()
        # server spans carry the caller identity
        srcs = {e["args"].get("src") for e in evs
                if e.get("cat") == "kvserver" and e.get("args")}
        assert srcs and all(s for s in srcs)
        # flow events pair up per trace id ("s" client side, "f" server)
        flows = [e for e in evs if e.get("ph") in ("s", "f")]
        per_id = {}
        for e in flows:
            per_id.setdefault(e["id"], set()).add(e["ph"])
        assert any(v == {"s", "f"} for v in per_id.values())
    finally:
        srv.stop()


def test_telemetry_off_keeps_plain_envelope():
    from mxnet_tpu.kvstore_server import ServerClient, start_server

    assert not telemetry.enabled()
    srv = start_server(port=0, num_workers=1)
    try:
        with ServerClient("127.0.0.1", srv.addr[1]) as cli:
            ent = cli._submit(("pull_part", "nope", 0, 1))
            ent["event"].wait()
            assert len(ent["env"]) == 4  # no ctx element on the wire
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# 2 workers + 1 server as real processes: traces merge into ONE timeline,
# metrics federate into ONE endpoint
# ---------------------------------------------------------------------------
_WORKER_SRC = r"""
import os, sys, time
import numpy as np
from mxnet_tpu import telemetry
from mxnet_tpu.kvstore_server import ServerClient

rank = int(os.environ["DMLC_WORKER_ID"])
port = int(os.environ["DMLC_PS_ROOT_PORT"])
with ServerClient("127.0.0.1", port) as cli:
    cli.init("w", np.zeros(4, np.float32))
    for _ in range(3):
        cli.push("w", np.ones(4, np.float32))
        cli.pull("w")
    cli.multi([("init", "m%d" % rank, np.ones(2, np.float32))])
telemetry.gauge("mxtpu_step_last_ms").set(5.0 + rank)
telemetry.distributed.push_once()
"""


@pytest.mark.slow
def test_fleet_trace_merge_and_metrics_aggregation(tmp_path):
    port = _free_port()
    agg = FleetAggregator()
    agg.start()
    base = dict(os.environ,
                JAX_PLATFORMS="cpu",
                PYTHONPATH=REPO + (os.pathsep + os.environ["PYTHONPATH"]
                                   if os.environ.get("PYTHONPATH") else ""),
                DMLC_PS_ROOT_URI="127.0.0.1",
                DMLC_PS_ROOT_PORT=str(port),
                DMLC_NUM_WORKER="2",
                MXNET_TELEMETRY="1",
                MXNET_TELEMETRY_DIR=str(tmp_path),
                MXNET_TELEMETRY_AGG_ADDR=agg.addr,
                MXNET_TELEMETRY_AGG_INTERVAL="0.2")
    server = subprocess.Popen(
        [sys.executable, "-c", "import mxnet_tpu"],
        env=dict(base, DMLC_ROLE="server"), cwd=REPO)
    workers = []
    try:
        for r in range(2):
            workers.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER_SRC],
                env=dict(base, DMLC_WORKER_ID=str(r)), cwd=REPO))
        for w in workers:
            assert w.wait(timeout=120) == 0
        # -- fleet metrics: one page, all three processes, rank labels --
        deadline = time.monotonic() + 30
        page = ""
        while time.monotonic() < deadline:
            page = urllib.request.urlopen(
                "http://%s/metrics" % agg.addr, timeout=5).read().decode()
            if all(s in page for s in
                   ('role="worker",rank="0"', 'role="worker",rank="1"',
                    'role="server"', 'mxtpu_fleet_step_ms{stat="min"} 5')):
                break
            time.sleep(0.2)
        assert 'role="worker",rank="0"' in page
        assert 'role="worker",rank="1"' in page
        assert 'role="server"' in page, page
        assert 'mxtpu_fleet_step_ms{stat="min"} 5' in page
        assert 'mxtpu_fleet_step_ms{stat="max"} 6' in page
        assert "mxtpu_kvsrv_rpc_push_ms_count" in page
        from mxnet_tpu.kvstore_server import ServerClient

        with ServerClient("127.0.0.1", port) as cli:
            cli.stop_server()
        assert server.wait(timeout=60) == 0
    finally:
        for p in workers + [server]:
            if p.poll() is None:
                p.kill()
        agg.stop()

    # -- trace merge: worker + server dumps -> one validated timeline --
    paths = sorted(glob.glob(str(tmp_path / "trace-*.json")))
    names = {os.path.basename(p) for p in paths}
    assert {"trace-worker0.json", "trace-worker1.json",
            "trace-server0.json"} <= names, names
    merged = str(tmp_path / "fleet.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_merge.py"),
         "-o", merged] + paths,
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    payload = json.load(open(merged))
    telemetry.validate_trace(payload)
    evs = payload["traceEvents"]
    procs = {e["args"]["name"]: e["pid"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert {"worker0", "worker1", "server0"} <= set(procs)
    # the acceptance bar: a worker push span and the server handler span
    # share a trace id while living on DIFFERENT process tracks
    linked = 0
    for role in ("worker0", "worker1"):
        cpid, spid = procs[role], procs["server0"]
        ctraces = {e["args"]["trace"] for e in evs
                   if e.get("pid") == cpid and e.get("cat") == "kvclient"
                   and e.get("args") and e["name"] == "kv.client.push"}
        straces = {e["args"]["trace"] for e in evs
                   if e.get("pid") == spid and e.get("cat") == "kvserver"
                   and e.get("args")}
        linked += len(ctraces & straces)
    assert linked > 0
    # thread tracks are role/rank-prefixed, so they never collide
    tnames = [e["args"]["name"] for e in evs
              if e.get("ph") == "M" and e.get("name") == "thread_name"]
    assert tnames and all("/" in n for n in tnames)


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------
def test_straggler_event_on_delayed_rank(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY_STRAGGLER_MULT", "1.5")
    monkeypatch.setenv("MXNET_TELEMETRY_STRAGGLER_MIN_MS", "50")
    telemetry.enable(trace=False)
    from mxnet_tpu.kvstore_server import KVStoreServer

    srv = KVStoreServer(port=0, num_workers=2, sync_mode=True)
    try:
        srv._dispatch(("init", "w", np.zeros(4, np.float32)))
        srv._dispatch(("push", "w", np.ones(4, np.float32), 0))
        time.sleep(0.25)
        srv._dispatch(("push", "w", np.ones(4, np.float32), 1))
        evs = [e for e in telemetry.events() if e["kind"] == "straggler"]
        assert len(evs) == 1
        ev = evs[0]
        assert ev["rank"] == 1 and ev["key"] == "w"
        assert ev["lat_ms"] > 1.5 * ev["median_ms"]
        assert ev["round_size"] == 2
        # a prompt round raises no new event
        srv._dispatch(("push", "w", np.ones(4, np.float32), 0))
        srv._dispatch(("push", "w", np.ones(4, np.float32), 1))
        evs = [e for e in telemetry.events() if e["kind"] == "straggler"]
        assert len(evs) == 1
        text = telemetry.render_prometheus()
        assert 'mxtpu_kvsrv_stragglers_total{rank="1"} 1' in text
        assert "mxtpu_kvsrv_round_skew_ms" in text
        # StepMonitor summaries surface the per-rank counts
        mon = telemetry.StepMonitor(telemetry)
        assert mon.report()["stragglers"] == {"1": 1}
    finally:
        srv._server.server_close()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_flight_recorder_dump_contents(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY_POSTMORTEM_DIR", str(tmp_path))
    telemetry.enable(trace=True)
    with telemetry.span("doomed-step"):
        pass
    telemetry.log_event("last-words", detail=42)
    telemetry.counter("mxtpu_doom_total").inc()
    path = telemetry.flight_recorder.dump("unit-test", extra={"k": "v"})
    assert path and os.path.exists(path)
    assert telemetry.flight_recorder.last_path() == path
    post = json.load(open(path))
    assert post["reason"] == "unit-test"
    assert post["extra"] == {"k": "v"}
    assert post["pid"] == os.getpid()
    assert any(s["name"] == "doomed-step" for s in post["spans"])
    assert any(e["kind"] == "last-words" for e in post["events"])
    assert post["metrics"]["mxtpu_doom_total"] == 1


def test_flight_recorder_noop_when_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY_POSTMORTEM_DIR", str(tmp_path))
    assert telemetry.flight_recorder.dump("nope") is None
    assert not list(tmp_path.iterdir())


def test_fault_kill_leaves_postmortem(tmp_path):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + (os.pathsep + os.environ["PYTHONPATH"]
                                  if os.environ.get("PYTHONPATH") else ""),
               MXNET_TELEMETRY="1",
               MXNET_TELEMETRY_DIR=str(tmp_path),
               MXNET_FAULTS_SPEC="boom.op:kill=1@#1",
               MXNET_FAULTS_SEED="0")
    r = subprocess.run(
        [sys.executable, "-c",
         "from mxnet_tpu import faults, telemetry\n"
         "with telemetry.span('pre-crash'):\n"
         "    pass\n"
         "faults.fire('boom.op')\n"],
        env=env, cwd=REPO, timeout=120)
    assert r.returncode == 137
    pm = glob.glob(str(tmp_path / "postmortem-*.json"))
    assert len(pm) == 1
    post = json.load(open(pm[0]))
    assert post["reason"] == "fault-kill:boom.op"
    assert any(s["name"] == "pre-crash" for s in post["spans"])


def test_preemption_handler_dumps_postmortem(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY_POSTMORTEM_DIR", str(tmp_path))
    telemetry.enable(trace=False)
    from mxnet_tpu.kvstore import install_preemption_handler

    calls = []

    class _KV:
        def drain(self, timeout=None):
            calls.append("drain")
            return True

        def leave(self):
            calls.append("leave")

    handler = install_preemption_handler(_KV(), exit_process=False)
    handler()
    assert calls == ["drain", "leave"]
    pm = glob.glob(str(tmp_path / "postmortem-*.json"))
    assert len(pm) == 1
    assert json.load(open(pm[0]))["reason"] == "preemption-sigterm"


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_excepthook_dump_on_unhandled_thread_exception(tmp_path,
                                                       monkeypatch):
    import threading

    monkeypatch.setenv("MXNET_TELEMETRY_POSTMORTEM_DIR", str(tmp_path))
    telemetry.enable(trace=False)

    def boom():
        raise RuntimeError("thread went sideways")

    t = threading.Thread(target=boom, name="doomed-thread")
    t.start()
    t.join()
    pm = glob.glob(str(tmp_path / "postmortem-*.json"))
    assert len(pm) == 1
    post = json.load(open(pm[0]))
    assert post["reason"] == "thread-exception:RuntimeError"
    assert post["extra"]["thread"] == "doomed-thread"


# ---------------------------------------------------------------------------
# aggregator unit surface
# ---------------------------------------------------------------------------
def test_aggregator_relabels_and_derives_fleet_gauges():
    agg = FleetAggregator()
    agg.start()
    try:
        def push(role, rank, body):
            req = urllib.request.Request(
                "http://%s/push?role=%s&rank=%d" % (agg.addr, role, rank),
                data=body.encode(), method="POST")
            urllib.request.urlopen(req, timeout=5).close()

        push("worker", 0, "mxtpu_step_last_ms 5\nmxtpu_x_total{k=\"a\"} 2\n")
        push("worker", 1, "mxtpu_step_last_ms 9\n")
        push("server", 0, "mxtpu_kvsrv_round_skew_ms 3.5\n")
        page = urllib.request.urlopen(
            "http://%s/metrics" % agg.addr, timeout=5).read().decode()
        assert 'mxtpu_step_last_ms{role="worker",rank="0"} 5' in page
        assert 'mxtpu_step_last_ms{role="worker",rank="1"} 9' in page
        # existing labels merge with the federation labels
        assert 'mxtpu_x_total{k="a",role="worker",rank="0"} 2' in page
        assert "mxtpu_fleet_processes 3" in page
        assert 'mxtpu_fleet_step_ms{stat="min"} 5' in page
        assert 'mxtpu_fleet_step_ms{stat="median"} 7' in page
        assert 'mxtpu_fleet_step_ms{stat="max"} 9' in page
        assert "mxtpu_fleet_sync_skew_ms 3.5" in page
        health = json.loads(urllib.request.urlopen(
            "http://%s/healthz" % agg.addr, timeout=5).read().decode())
        assert health == {"status": "ok", "processes": 3}
        assert agg.processes() == [("server", "0"), ("worker", "0"),
                                   ("worker", "1")]
    finally:
        agg.stop()


def test_aggregator_keeps_models_distinguishable():
    """Two models pushing from ONE process must stay separate series:
    the model= label survives the role/rank relabel, per-tenant samples
    never merge, and the derived mxtpu_fleet_models gauge counts the
    distinct models (the platform's cost-attribution contract)."""
    agg = FleetAggregator()
    agg.start()
    try:
        def push(role, rank, body):
            req = urllib.request.Request(
                "http://%s/push?role=%s&rank=%d" % (agg.addr, role, rank),
                data=body.encode(), method="POST")
            urllib.request.urlopen(req, timeout=5).close()

        push("serving", 0,
             'mxtpu_platform_fault_ins_total{model="resnet"} 3\n'
             'mxtpu_platform_fault_ins_total{model="dlrm"} 1\n'
             'mxtpu_requests_total{model="resnet",tenant="acme"} 10\n'
             'mxtpu_requests_total{model="resnet",tenant="globex"} 7\n')
        push("serving", 1,
             'mxtpu_platform_fault_ins_total{model="lm"} 2\n')
        page = urllib.request.urlopen(
            "http://%s/metrics" % agg.addr, timeout=5).read().decode()
        # model label preserved through relabeling, one series per model
        assert ('mxtpu_platform_fault_ins_total{model="resnet",'
                'role="serving",rank="0"} 3') in page
        assert ('mxtpu_platform_fault_ins_total{model="dlrm",'
                'role="serving",rank="0"} 1') in page
        assert ('mxtpu_platform_fault_ins_total{model="lm",'
                'role="serving",rank="1"} 2') in page
        # no cross-tenant merging: both tenants keep their own sample
        assert ('mxtpu_requests_total{model="resnet",tenant="acme",'
                'role="serving",rank="0"} 10') in page
        assert ('mxtpu_requests_total{model="resnet",tenant="globex",'
                'role="serving",rank="0"} 7') in page
        # derived gauge: distinct models across the whole fleet
        assert "mxtpu_fleet_models 3" in page
    finally:
        agg.stop()


def test_proc_identity_follows_dmlc_contract(monkeypatch):
    from mxnet_tpu.telemetry.distributed import proc_identity, proc_label

    monkeypatch.setenv("DMLC_ROLE", "server")
    monkeypatch.setenv("DMLC_SERVER_ID", "2")
    assert proc_identity() == ("server", 2)
    assert proc_label() == "server2"
    monkeypatch.setenv("DMLC_ROLE", "worker")
    monkeypatch.setenv("DMLC_WORKER_ID", "1")
    assert proc_identity() == ("worker", 1)
    monkeypatch.delenv("DMLC_ROLE")
    assert proc_identity() == ("worker", 1)  # DMLC_WORKER_ID fallback
    monkeypatch.setenv("MXNET_TELEMETRY_ROLE", "evaluator")
    assert proc_identity()[0] == "evaluator"

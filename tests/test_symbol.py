"""Symbol composition, shape/type inference, JSON round-trip
(reference: tests/python/unittest/test_symbol.py + test_infer_shape.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=10, name="fc1")
    net = sym.Activation(data=net, act_type="relu", name="relu1")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_compose_and_list():
    net = _mlp()
    args = net.list_arguments()
    assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                    "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 100))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (10, 100)
    assert d["fc1_bias"] == (10,)
    assert d["fc2_weight"] == (4, 10)
    assert out_shapes == [(32, 4)]


def test_infer_shape_backward_deduction():
    # shape flows backward from fc weight to the input
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=3, name="fc")
    arg_shapes, _, _ = net.infer_shape(fc_weight=(3, 7), fc_bias=(3,),
                                       data=(5, 7))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["data"] == (5, 7)


def test_deep_chain_shape_convergence():
    # VERDICT weak #6: deep chains must reach fixed point (not capped at 3)
    net = sym.Variable("data")
    for i in range(10):
        net = sym.FullyConnected(data=net, num_hidden=8, name="fc%d" % i)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 8))
    assert all(s is not None for s in arg_shapes)
    assert out_shapes == [(2, 8)]


def test_infer_type():
    net = _mlp()
    arg_types, out_types, _ = net.infer_type(data=np.float32)
    assert all(t == np.float32 for t in arg_types)


def test_internals_and_getitem():
    net = _mlp()
    internals = net.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments()[:1] == ["data"]


def test_group():
    a, b = sym.Variable("a"), sym.Variable("b")
    g = sym.Group([a + b, a * b])
    assert len(g.list_outputs()) == 2


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    arg_shapes, out_shapes, _ = net2.infer_shape(data=(8, 20))
    assert out_shapes == [(8, 4)]


def test_json_roundtrip_with_user_attrs():
    # ADVICE medium: user attrs (lr_mult) must survive load_json
    with mx.AttrScope(lr_mult="0.1"):
        data = sym.Variable("data")
        net = sym.FullyConnected(data=data, num_hidden=2, name="fc")
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    attrs = net2.attr_dict()
    assert attrs.get("fc", {}).get("lr_mult") == "0.1"


def test_save_load_file():
    net = _mlp()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "net.json")
        net.save(path)
        net2 = sym.load(path)
    assert net2.list_arguments() == net.list_arguments()


def test_symbol_arithmetic_composition():
    a, b = sym.Variable("a"), sym.Variable("b")
    s = (a + b) * 2.0 - a / b
    ex = s.bind(mx.cpu(), {"a": mx.nd.array([2.0, 4.0]),
                           "b": mx.nd.array([1.0, 2.0])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [4.0, 10.0], rtol=1e-5)


def test_variable_shape_attr():
    v = sym.Variable("x", shape=(3, 4))
    arg_shapes, _, _ = (v * 2.0).infer_shape()
    assert arg_shapes == [(3, 4)]


# A minimal pre-NNVM-era graph (op params live in a separate "param" dict
# of strings; "{input}_lr_mult" multipliers sit on the op node) — inline
# fallback fixture so the legacy-load path has coverage without the
# reference tree.
_LEGACY_JSON = """{
  "nodes": [
    {"op": "null", "param": {}, "name": "data", "inputs": [],
     "backward_source_id": -1},
    {"op": "null", "param": {}, "name": "fc1_weight", "inputs": [],
     "backward_source_id": -1},
    {"op": "null", "param": {}, "name": "fc1_bias", "inputs": [],
     "backward_source_id": -1},
    {"op": "FullyConnected",
     "param": {"no_bias": "False", "num_hidden": "10"},
     "name": "fc1", "inputs": [[0, 0], [1, 0], [2, 0]],
     "backward_source_id": -1,
     "attr": {"ctx_group": "stage1", "weight_lr_mult": "1.2"}},
    {"op": "null", "param": {}, "name": "softmax_label", "inputs": [],
     "backward_source_id": -1},
    {"op": "Softmax", "param": {"grad_scale": "1"}, "name": "softmax",
     "inputs": [[3, 0], [4, 0]], "backward_source_id": -1}
  ],
  "arg_nodes": [0, 1, 2, 4],
  "heads": [[5, 0]]
}"""


def _check_legacy_graph(net, in_dim):
    args = net.list_arguments()
    assert args[0] == "data" and "fc1_weight" in args \
        and "softmax_label" in args
    _, out_shapes, _ = net.infer_shape(data=(4, in_dim))
    assert out_shapes[0] == (4, 10)
    # op-node attrs survive; "{input}_lr_mult" was pushed down onto the
    # variable as __lr_mult__ (legacy_json_util.cc:60-84)
    attrs = net.attr_dict()
    assert attrs.get("fc1", {}).get("ctx_group") == "stage1"
    assert attrs.get("fc1_weight", {}).get("__lr_mult__") == "1.2"
    assert "weight_lr_mult" not in attrs.get("fc1", {})
    # and the loaded graph round-trips through the current format
    assert mx.sym.load_json(net.tojson()).list_arguments() == args


def test_load_legacy_pre_nnvm_json_inline():
    _check_legacy_graph(mx.sym.load_json(_LEGACY_JSON), 20)


def test_load_legacy_pre_nnvm_json_reference_fixture():
    """The reference's own back-compat fixture, when the tree is present
    (tests/python/unittest/save_000800.json)."""
    import os

    import pytest

    path = "/root/reference/tests/python/unittest/save_000800.json"
    if not os.path.exists(path):
        pytest.skip("reference fixture not available")
    with open(path) as f:
        net = mx.sym.load_json(f.read())
    _check_legacy_graph(net, 100)


def test_call_composition():
    """Reference symbol.py:212-230: x(y) / x(data=y) composes inputs."""
    import numpy as np

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                no_bias=True, name="fc")
    pre = mx.sym.Variable("raw") * 3.0
    composed = net(data=pre)
    assert composed.list_arguments() == ["raw", "fc_weight"]
    ex = composed.simple_bind(mx.cpu(), raw=(2, 4), grad_req="null")
    w = np.random.RandomState(0).randn(2, 4).astype("f")
    ex.arg_dict["fc_weight"][:] = w
    x = np.ones((2, 4), "f")
    out = ex.forward(raw=x)[0].asnumpy()
    np.testing.assert_allclose(out, (3 * x) @ w.T, rtol=1e-5)

    # positional maps to list_arguments order; mixing raises
    composed2 = net(pre)
    assert composed2.tojson() == composed.tojson()
    with pytest.raises(TypeError, match="not both"):
        net(pre, data=pre)
    with pytest.raises(TypeError, match="positional inputs"):
        net(pre, pre, pre)
    # unknown names raise (compose contract)
    with pytest.raises(ValueError, match="not free arguments"):
        net(nonexistent=pre)


def test_debug_str():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="fc")
    s = net.debug_str()
    assert "Variable:data" in s and "Name=fc" in s
    assert "num_hidden=2" in s and "Outputs:" in s

"""mxnet_tpu.serving tests — batch coalescing, bucket padding, deadlines,
admission control, graceful drain, metrics, HTTP front end.  All CPU-only
and fast: the model is a tiny FullyConnected net and warmup is enabled
only where the test is about steady-state compile behaviour."""
import json
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving


IN_DIM = 6
HID = 3


def _tiny_model(seed=0):
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=HID,
                                name="fc")
    params = {
        "fc_weight": mx.nd.array(rng.randn(HID, IN_DIM).astype(np.float32)),
        "fc_bias": mx.nd.array(rng.randn(HID).astype(np.float32)),
    }
    return net, params


def _reference_outputs(net, params, X):
    pred = mx.Predictor(net, dict(params), {"data": (1, IN_DIM)})
    return np.stack([pred.forward(data=X[i:i + 1])[0].asnumpy()[0]
                     for i in range(len(X))])


def test_pow2_buckets():
    assert serving.pow2_buckets(1) == (1,)
    assert serving.pow2_buckets(16) == (1, 2, 4, 8, 16)
    assert serving.pow2_buckets(12) == (1, 2, 4, 8, 12)
    with pytest.raises(ValueError):
        serving.pow2_buckets(0)


def test_bucketed_predictor_padding_matches_per_request():
    """Padded bucketed execution is numerically the per-request forward."""
    net, params = _tiny_model()
    bp = serving.BucketedPredictor(net, dict(params), {"data": (IN_DIM,)},
                                   buckets=(1, 2, 4, 8))
    assert bp.bucket_for(1) == 1
    assert bp.bucket_for(3) == 4
    assert bp.bucket_for(8) == 8
    with pytest.raises(mx.MXNetError):
        bp.bucket_for(9)
    X = np.random.RandomState(1).randn(5, IN_DIM).astype(np.float32)
    ref = _reference_outputs(net, params, X)
    bucket, per_item = bp.forward_batch([{"data": X[i]} for i in range(5)])
    assert bucket == 8  # 5 requests pad up to the next bucket
    assert len(per_item) == 5
    for i in range(5):
        np.testing.assert_allclose(per_item[i][0], ref[i], rtol=1e-5,
                                   atol=1e-6)


def test_concurrent_submits_coalesce_into_buckets():
    """Acceptance criterion: 64 concurrent single-item requests run in at
    most len(buckets) distinct compiled shapes and strictly fewer executor
    invocations than 64 sequential Predictor.forward calls — asserted via
    the metrics batch-size histogram AND a wrapper around the real
    executor forward of every bucket predictor."""
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (16, IN_DIM)},
                                  max_wait_us=20000, max_queue=256)
    try:
        # count true post-warmup executor invocations per bucket predictor
        exec_calls = {"n": 0}
        count_lock = threading.Lock()
        for rep in srv._replicas:
            for pred in rep._preds.values():
                orig = pred._exec.forward

                def counted(*a, _orig=orig, **kw):
                    with count_lock:
                        exec_calls["n"] += 1
                    return _orig(*a, **kw)

                pred._exec.forward = counted

        X = np.random.RandomState(2).randn(64, IN_DIM).astype(np.float32)
        with ThreadPoolExecutor(max_workers=16) as pool:
            futs = list(pool.map(lambda i: srv.submit(data=X[i]), range(64)))
        results = [f.result(timeout=60) for f in futs]

        ref = _reference_outputs(net, params, X)
        for i in range(64):
            np.testing.assert_allclose(results[i][0], ref[i], rtol=1e-5,
                                       atol=1e-6)

        snap = srv.metrics.snapshot()
        hist = snap["batch_size_hist"]
        # every flush ran at a pre-compiled bucket shape: at most
        # len(buckets) distinct shapes, no novel-shape compiles
        assert set(hist) <= set(srv.buckets)
        assert len(hist) <= len(srv.buckets)
        # measurably fewer executor invocations than 64 sequential
        # Predictor.forward calls, and the histogram reports them honestly
        assert sum(hist.values()) == snap["batches_total"] == exec_calls["n"]
        assert exec_calls["n"] < 64
        assert sum(n * c for n, c in snap["occupancy_hist"].items()) == 64
        assert snap["requests_completed"] == 64
    finally:
        srv.stop()


def test_deadline_expiry():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  max_wait_us=200000, warmup=False)
    try:
        x = np.zeros(IN_DIM, np.float32)
        fut = srv.submit(deadline_ms=10, data=x)
        with pytest.raises(serving.DeadlineExceededError):
            fut.result(timeout=30)
        assert srv.metrics.snapshot()["requests_expired"] == 1
    finally:
        srv.stop()


def test_queue_full_rejection():
    net, params = _tiny_model()
    # flush deadline far out and batch bigger than the queue bound, so
    # submits pile up in the queue until admission control trips
    srv = serving.InferenceServer(net, dict(params), {"data": (8, IN_DIM)},
                                  max_wait_us=300000, max_queue=4,
                                  warmup=False)
    try:
        x = np.zeros(IN_DIM, np.float32)
        futs = [srv.submit(data=x) for _ in range(4)]
        with pytest.raises(serving.QueueFullError):
            srv.submit(data=x)
        assert srv.metrics.snapshot()["requests_rejected"] == 1
        # the queued four still complete once the flush deadline fires
        for f in futs:
            assert len(f.result(timeout=30)) == 1
    finally:
        srv.stop()


def test_graceful_drain():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (8, IN_DIM)},
                                  max_wait_us=500000, warmup=False)
    X = np.random.RandomState(3).randn(6, IN_DIM).astype(np.float32)
    futs = [srv.submit(data=X[i]) for i in range(6)]
    srv.stop(drain=True)  # flushes the queue before the workers exit
    ref = _reference_outputs(net, params, X)
    for i in range(6):
        np.testing.assert_allclose(futs[i].result(timeout=1)[0], ref[i],
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(serving.ServerClosedError):
        srv.submit(data=X[0])


def test_stop_without_drain_fails_pending():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (8, IN_DIM)},
                                  max_wait_us=500000, warmup=False)
    fut = srv.submit(data=np.zeros(IN_DIM, np.float32))
    srv.stop(drain=False)
    with pytest.raises(serving.ServerClosedError):
        fut.result(timeout=1)


def test_input_validation():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (2, IN_DIM)},
                                  warmup=False)
    try:
        with pytest.raises(mx.MXNetError):
            srv.submit(data=np.zeros(IN_DIM + 1, np.float32))
        with pytest.raises(mx.MXNetError):
            srv.submit(bogus=np.zeros(IN_DIM, np.float32))
        with pytest.raises(mx.MXNetError):
            srv.submit()
        # a unit batch axis is accepted and squeezed
        out = srv.predict(data=np.zeros((1, IN_DIM), np.float32))
        assert out[0].shape == (HID,)
    finally:
        srv.stop()


def test_metrics_text_output():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    try:
        srv.predict(data=np.zeros(IN_DIM, np.float32))
        text = srv.metrics_text()
        # registry-backed: the server's per-instance registry is a live
        # collector of the shared telemetry exposition
        from mxnet_tpu import telemetry
        assert "mxtpu_serving_requests_total 1" in \
            telemetry.render_prometheus()
    finally:
        srv.stop()
    assert "mxtpu_serving_requests_total 1" in text
    assert "mxtpu_serving_requests_completed 1" in text
    assert 'mxtpu_serving_batch_size{bucket="1"} 1' in text
    assert 'mxtpu_serving_latency_ms{quantile="0.99"}' in text
    assert "mxtpu_serving_qps" in text
    snap = srv.metrics.snapshot()
    assert snap["qps"] > 0
    assert snap["latency_ms_p50"] > 0


def test_batches_emit_profiler_frames(tmp_path):
    net, params = _tiny_model()
    trace = str(tmp_path / "serving_trace.json")
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    try:
        mx.profiler.profiler_set_config(mode="all", filename=trace)
        mx.profiler.profiler_set_state("run")
        srv.predict(data=np.zeros(IN_DIM, np.float32))
        mx.profiler.profiler_set_state("stop")
        mx.profiler.dump_profile()
    finally:
        srv.stop()
    with open(trace) as f:
        events = json.load(f)["traceEvents"]
    spans = [e for e in events if e["name"].startswith("serving/batch")]
    assert spans and spans[0]["cat"] == "serving"


def test_multi_replica_dispatch():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  ctx=[mx.cpu(0), mx.cpu(1)],
                                  max_wait_us=2000, warmup=False)
    try:
        assert len(srv._replicas) == 2
        X = np.random.RandomState(4).randn(12, IN_DIM).astype(np.float32)
        futs = [srv.submit(data=X[i]) for i in range(12)]
        ref = _reference_outputs(net, params, X)
        for i in range(12):
            np.testing.assert_allclose(futs[i].result(timeout=60)[0],
                                       ref[i], rtol=1e-5, atol=1e-6)
        assert srv.metrics.snapshot()["requests_completed"] == 12
    finally:
        srv.stop()


def test_http_endpoint():
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    try:
        host, port = srv.serve_http()
        base = "http://%s:%d" % (host, port)
        x = list(range(IN_DIM))
        body = json.dumps({"inputs": {"data": x}}).encode()
        resp = urllib.request.urlopen(urllib.request.Request(
            base + "/predict", data=body,
            headers={"Content-Type": "application/json"}), timeout=30)
        out = json.loads(resp.read())["outputs"]
        ref = _reference_outputs(
            net, params, np.asarray(x, np.float32)[None])[0]
        np.testing.assert_allclose(np.asarray(out[0]), ref, rtol=1e-5,
                                   atol=1e-6)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as m:
            assert "mxtpu_serving_requests_total" in m.read().decode()
        with urllib.request.urlopen(base + "/healthz", timeout=10) as h:
            assert h.read() == b"ok"
        # malformed input -> 400, not a hung or dropped connection
        bad = json.dumps({"inputs": {"data": [1.0]}}).encode()
        try:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict", data=bad,
                headers={"Content-Type": "application/json"}), timeout=10)
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    finally:
        srv.stop()


def test_from_checkpoint(tmp_path):
    """A trained Module checkpoint serves through the batching tier and
    matches the plain Predictor on the same checkpoint."""
    np.random.seed(5)
    X = np.random.randn(40, IN_DIM).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="out")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "served")
    mod.save_checkpoint(prefix, 1)

    srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000, warmup=False)
    try:
        out = srv.predict(data=X[0])
        pred = mx.Predictor.from_checkpoint(prefix, 1, {"data": (1, IN_DIM)})
        ref = pred.forward(data=X[0:1])[0].asnumpy()[0]
        np.testing.assert_allclose(out[0], ref, rtol=1e-5, atol=1e-6)
    finally:
        srv.stop()


def test_ready_lifecycle_and_readyz_endpoint():
    """Readiness (may I take traffic?) is distinct from liveness (am I
    alive?): /readyz must say 503 while starting, warming, or stopped,
    with the why-not in the body, while /healthz keeps its dead-worker
    semantics untouched."""
    import urllib.error

    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  warmup=False, start=False)
    try:
        assert not srv.ready()
        assert srv.ready_state() == "starting"
        srv.start()
        assert srv.ready()
        assert srv.ready_state() == "ready"

        host, port = srv.serve_http()
        base = "http://%s:%d" % (host, port)
        with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
            assert r.read() == b"ready"
        # re-enter the warming window: /readyz flips to 503 "warming"
        # while /healthz stays 200 — the router drains traffic off a
        # warming replica without the orchestrator killing it
        srv._warmed = False
        assert srv.ready_state() == "warming"
        try:
            urllib.request.urlopen(base + "/readyz", timeout=10)
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            assert json.loads(exc.read())["status"] == "warming"
        with urllib.request.urlopen(base + "/healthz", timeout=10) as h:
            assert h.read() == b"ok"
        srv.warmup()
        assert srv.ready()
        assert srv.cold_bucket_runs() == 0
    finally:
        srv.stop()
    assert not srv.ready()
    assert srv.ready_state() == "stopped"


def test_stop_is_idempotent():
    """A second stop() (any drain value) is a no-op: it must not re-fail
    futures, re-join workers, or raise — and submit() after stop raises
    the typed ServerClosedError immediately instead of queueing into the
    dead batcher."""
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (8, IN_DIM)},
                                  max_wait_us=500000, warmup=False)
    X = np.random.RandomState(6).randn(3, IN_DIM).astype(np.float32)
    futs = [srv.submit(data=X[i]) for i in range(3)]
    srv.stop(drain=True)
    results = [f.result(timeout=1) for f in futs]
    assert len(results) == 3
    srv.stop(drain=False)  # no-op: the drained results stay results
    srv.stop()
    assert all(f.exception() is None for f in futs)
    with pytest.raises(serving.ServerClosedError):
        srv.submit(data=X[0])


def test_stop_releases_device_memory():
    """stop() must release device-resident params and executables — a
    paged-out model cannot pin HBM.  resident_bytes() is the proof: >0
    while serving, 0 after stop; cold_bucket_runs() survives the release
    so warm-start accounting still reads correctly post-mortem."""
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  warmup=True)
    x = np.zeros(IN_DIM, np.float32)
    srv.submit(data=x).result(timeout=30)
    assert srv.resident_bytes() > 0
    cold_before = srv.cold_bucket_runs()
    srv.stop(drain=True)
    assert srv.resident_bytes() == 0
    assert srv.cold_bucket_runs() == cold_before


def test_http_deadline_header():
    """X-Deadline-Ms on /predict must reach submit(deadline_ms=...): a
    request that can't make its deadline dies as a 504, not as unbounded
    queueing."""
    import urllib.error

    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (8, IN_DIM)},
                                  max_wait_us=200000, warmup=False)
    try:
        host, port = srv.serve_http()
        body = json.dumps(
            {"inputs": {"data": list(range(IN_DIM))}}).encode()
        try:
            urllib.request.urlopen(urllib.request.Request(
                "http://%s:%d/predict" % (host, port), data=body,
                headers={"Content-Type": "application/json",
                         "X-Deadline-Ms": "10"}), timeout=30)
            raise AssertionError("expected HTTP 504")
        except urllib.error.HTTPError as exc:
            assert exc.code == 504
        assert srv.metrics.snapshot()["requests_expired"] == 1
    finally:
        srv.stop()


def test_healthz_degraded_when_worker_thread_dies():
    """A dead replica worker must flip /healthz to 503 degraded (with the
    dead thread named) and bump the worker_crashes counter — a server
    that looks alive but silently lost its executor loop is the failure
    mode health checks exist for."""
    import urllib.error

    net, params = _tiny_model()
    srv = serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    try:
        host, port = srv.serve_http()
        base = "http://%s:%d" % (host, port)
        with urllib.request.urlopen(base + "/healthz", timeout=10) as h:
            assert h.read() == b"ok"
        assert srv.health() == ("ok", [])

        # make the worker's NEXT _collect() blow up; the current request
        # completes normally, then the loop crashes
        def boom():
            raise RuntimeError("injected worker crash")

        prev_hook = threading.excepthook  # keep the traceback out of logs
        threading.excepthook = lambda args: None
        try:
            srv._batcher._collect = boom
            srv.predict(data=np.zeros(IN_DIM, np.float32))
            deadline = time.monotonic() + 10.0
            while not srv._batcher.dead_workers():
                assert time.monotonic() < deadline, "worker never died"
                time.sleep(0.02)
        finally:
            threading.excepthook = prev_hook

        status, dead = srv.health()
        assert status == "degraded"
        assert any("injected worker crash" in d for d in dead)
        assert srv.metrics.snapshot()["worker_crashes"] == 1
        try:
            urllib.request.urlopen(base + "/healthz", timeout=10)
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
            body = json.loads(exc.read())
            assert body["status"] == "degraded"
            assert body["dead_workers"]
        text = srv.metrics_text()
        assert "mxtpu_serving_worker_crashes 1" in text
    finally:
        srv.stop()


def test_drain_deadline_force_cancels_wedged_worker():
    """A wedged batch worker must not hang retirement: stop(drain=True)
    past MXNET_SERVING_DRAIN_TIMEOUT_MS force-cancels every remaining
    future with DrainTimeoutError instead of waiting forever."""
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, params, {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    wedge = threading.Event()
    real_forward = srv._replicas[0].forward_batch

    def wedged_forward(items):
        wedge.wait()                    # the worker is stuck mid-batch
        return real_forward(items)

    srv._replicas[0].forward_batch = wedged_forward
    futs = [srv.submit(data=np.zeros(IN_DIM, np.float32))
            for _ in range(6)]
    t0 = time.monotonic()
    srv.stop(drain=True, timeout_ms=300)
    assert time.monotonic() - t0 < 10.0     # bounded, not forever
    cancelled = 0
    for f in futs:
        assert f.done()
        try:
            f.result(timeout=0)
        except serving.DrainTimeoutError:
            cancelled += 1
    assert cancelled == len(futs)
    wedge.set()                             # unwedge; late completion is
    time.sleep(0.1)                         # dropped, never raised


def test_drain_completes_before_deadline_without_cancel():
    """The hard deadline is a backstop: a healthy drain still flushes
    every queued request successfully."""
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, params, {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    futs = [srv.submit(data=np.zeros(IN_DIM, np.float32))
            for _ in range(6)]
    srv.stop(drain=True, timeout_ms=30000)
    for f in futs:
        assert f.result(timeout=0) is not None


def test_begin_drain_flips_readiness_only():
    """begin_drain quiesces arrivals (readyz 503) while the server keeps
    completing work — the scale-in first step."""
    net, params = _tiny_model()
    srv = serving.InferenceServer(net, params, {"data": (4, IN_DIM)},
                                  max_wait_us=1000, warmup=False)
    try:
        fut = srv.submit(data=np.zeros(IN_DIM, np.float32))
        srv.begin_drain()
        assert srv.ready_state() == "draining" and not srv.ready()
        assert fut.result(timeout=30) is not None   # in-flight completes
        status, _ = srv.health()
        assert status == "ok"                       # liveness untouched
    finally:
        srv.stop()

"""Global-mesh fused dist training test (4 workers): Module.fit with
kvstore='dist_sync' must run the FUSED train step (fwd+bwd+psum+update as one
XLA program over a mesh spanning all processes, kvstore as control-plane
facade) and produce parameters matching a single-process oracle trained on
the concatenated global batches.

Reference semantics being reproduced: server-side sum-until-NumWorkers then
update (/root/reference/src/kvstore/kvstore_dist_server.h:164-200) ==
summed global-batch gradient + identical replicated update.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

B_LOCAL = 8
NBATCH = 5
EPOCHS = 3


def make_net():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def init_params():
    rng = np.random.RandomState(42)
    return {
        "fc1_weight": mx.nd.array(rng.randn(16, 8).astype(np.float32) * 0.1),
        "fc1_bias": mx.nd.zeros((16,)),
        "fc2_weight": mx.nd.array(rng.randn(2, 16).astype(np.float32) * 0.1),
        "fc2_bias": mx.nd.zeros((2,)),
    }


def main():
    kv = mx.kvstore.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    assert nworker == 4

    rng = np.random.RandomState(7)
    n_per = B_LOCAL * NBATCH
    X = rng.randn(nworker * n_per, 8).astype(np.float32)
    w = rng.randn(8)
    y = (X @ w > 0).astype(np.float32)

    shard = slice(rank * n_per, (rank + 1) * n_per)
    train = mx.io.NDArrayIter(X[shard], y[shard], batch_size=B_LOCAL)

    opt_params = {"learning_rate": 0.5, "momentum": 0.9,
                  "rescale_grad": 1.0 / (B_LOCAL * nworker)}

    mod = mx.mod.Module(make_net(), context=mx.cpu())
    mod.fit(train, num_epoch=EPOCHS, kvstore=kv, optimizer="sgd",
            optimizer_params=dict(opt_params), arg_params=init_params(),
            allow_missing=False, initializer=None,
            eval_metric=mx.metric.Accuracy())
    assert mod._fused_ok, "dist_sync did not take the fused global-mesh path"
    assert mod._update_on_kvstore is False, \
        "kvstore should be a facade under the global mesh"
    args, _ = mod.get_params()

    # ---- single-process oracle: same global batches, one device ---------
    # global batch i == concat over ranks of each rank's i-th local batch
    Xg = np.concatenate([
        np.concatenate([X[r * n_per + i * B_LOCAL:
                          r * n_per + (i + 1) * B_LOCAL] for r in range(nworker)])
        for i in range(NBATCH)])
    yg = np.concatenate([
        np.concatenate([y[r * n_per + i * B_LOCAL:
                          r * n_per + (i + 1) * B_LOCAL] for r in range(nworker)])
        for i in range(NBATCH)])
    otrain = mx.io.NDArrayIter(Xg, yg, batch_size=B_LOCAL * nworker)
    omod = mx.mod.Module(make_net(), context=mx.cpu(), dist_mesh=False)
    omod.fit(otrain, num_epoch=EPOCHS, optimizer="sgd",
             optimizer_params=dict(opt_params), arg_params=init_params(),
             allow_missing=False, initializer=None)
    oargs, _ = omod.get_params()

    for k in sorted(args):
        np.testing.assert_allclose(
            args[k].asnumpy(), oargs[k].asnumpy(), rtol=2e-4, atol=2e-5,
            err_msg="param %s diverged from single-process oracle" % k)

    # cross-rank bitwise equality of the trained replicas
    flat = np.concatenate([args[k].asnumpy().ravel() for k in sorted(args)])
    from jax.experimental import multihost_utils

    gathered = np.asarray(multihost_utils.process_allgather(
        jax.numpy.asarray(flat)))
    for r in range(nworker):
        np.testing.assert_array_equal(gathered[r], gathered[0])

    print("dist_fused_worker %d/%d OK (fused mesh path, oracle match)"
          % (rank, nworker), flush=True)


if __name__ == "__main__":
    main()

"""Generative serving tests — continuous batching + paged KV-cache.

The PR-12 acceptance criteria as assertions: continuous-batched greedy
decode is bit-identical to sequential decode, paged attention matches
the dense full-prefix recompute, admit/retire churns correctly under
length skew, pool exhaustion backpressures (and preempts) without
deadlocking, the decode loop never recompiles after warmup, the engine's
executables round-trip through AOT bundles with their own cache kinds,
and — chaos-marked — a replica killed mid-stream resumes on a survivor
with zero duplicated or dropped tokens.

All CPU-only: the model is a tiny transformer LM (vocab 64, 2 layers)
with deterministic random weights, so greedy argmax transcripts are
stable references.
"""
import json
import os
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile_cache as cc
from mxnet_tpu import faults, generation, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.generation import (DecodeEngine, KVPoolExhaustedError,
                                  PagedKVPool)
from mxnet_tpu.serving import QueueFullError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, LAYERS, HEADS, HID, S = 64, 2, 2, 32, 32

SPEC = dict(vocab_size=V, num_layers=LAYERS, num_heads=HEADS, hidden=HID,
            max_seq_len=S, lane_buckets=(1, 2, 4), page_size=4,
            num_pages=48, prefill_len_buckets=(8, 16, 32))


def _lm_params(seed=0):
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=LAYERS,
                                       num_heads=HEADS, hidden=HID,
                                       seq_len=S)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(seed)
    params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    return net, params


_NET, _PARAMS = _lm_params()


def _prompts(rng, n, lo=2, hi=12):
    return [[int(t) for t in rng.randint(0, V, size=rng.randint(lo, hi))]
            for _ in range(n)]


def _sequential_reference(params, workload, **spec_overrides):
    """One request at a time through a fresh engine: the ground truth
    continuous batching must reproduce bit-identically."""
    spec = dict(SPEC, **spec_overrides)
    eng = DecodeEngine(params, **spec)
    try:
        return [eng.generate(p, n) for p, n in workload]
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# paged KV pool
# ---------------------------------------------------------------------------

def test_kv_pool_alloc_extend_free():
    pool = PagedKVPool(num_pages=8, page_size=4, num_layers=1,
                       num_heads=2, head_dim=4)
    assert pool.capacity == 7  # page 0 is reserved scratch
    assert pool.pages_for(1) == 1 and pool.pages_for(4) == 1
    assert pool.pages_for(5) == 2
    pool.alloc(0, 6)           # 2 pages
    pool.alloc(1, 4)           # 1 page
    assert pool.free_pages() == 4
    pool.extend(1, 5)          # crosses a page boundary: +1 page
    assert pool.free_pages() == 3
    assert pool.peak_pages == 4
    pool.free(0)
    assert pool.free_pages() == 5
    pool.free(1)
    assert pool.free_pages() == 7
    assert pool.peak_pages == 4  # high-water mark survives frees


def test_kv_pool_exhaustion_raises():
    pool = PagedKVPool(num_pages=4, page_size=4, num_layers=1,
                       num_heads=2, head_dim=4)
    pool.alloc(0, 12)  # 3 pages = full capacity
    with pytest.raises(KVPoolExhaustedError):
        pool.alloc(1, 1)
    pool.free(0)
    pool.alloc(1, 1)  # freed pages are reusable


# ---------------------------------------------------------------------------
# decode parity: the acceptance bit-identity checks
# ---------------------------------------------------------------------------

def test_continuous_batching_matches_sequential():
    """N concurrent mixed-length requests through one engine produce
    exactly the transcripts of one-at-a-time decoding."""
    rng = np.random.RandomState(7)
    workload = [(p, int(rng.randint(3, 10)))
                for p in _prompts(rng, 8)]
    ref = _sequential_reference(_PARAMS, workload)
    eng = DecodeEngine(_PARAMS, **SPEC)
    try:
        streams = [eng.submit(p, n) for p, n in workload]
        got = [s.result(timeout=120) for s in streams]
    finally:
        eng.stop()
    assert got == ref


def test_paged_attention_matches_dense_full_prefix():
    """The paged decode path agrees with the dense recompute: re-running
    the whole prefix through the full-length prefill executable and
    taking argmax at the last position yields the same greedy tokens."""
    from mxnet_tpu.models.transformer import get_transformer_lm_prefill

    sym = get_transformer_lm_prefill(V, LAYERS, HEADS, HID, seq_len=S,
                                     max_seq_len=S)
    pred = mx.Predictor(sym, dict(_PARAMS), {"data": (1, S)})
    buf = np.zeros((1, S), np.float32)

    def dense_decode(prompt, max_new):
        toks = list(prompt)
        gen = []
        for _ in range(max_new):
            buf[:] = 0
            buf[0, :len(toks)] = toks
            logits = pred.forward(data=buf)[0].asnumpy()
            nxt = int(np.argmax(logits[0, len(toks) - 1]))
            toks.append(nxt)
            gen.append(nxt)
        return gen

    rng = np.random.RandomState(11)
    workload = [(p, 6) for p in _prompts(rng, 4)]
    eng = DecodeEngine(_PARAMS, **SPEC)
    try:
        got = [eng.generate(p, n) for p, n in workload]
    finally:
        eng.stop()
    assert got == [dense_decode(p, n) for p, n in workload]


# ---------------------------------------------------------------------------
# admit/retire churn, backpressure, preemption
# ---------------------------------------------------------------------------

def test_admit_retire_under_length_skew():
    """More requests than lanes with skewed budgets (1..12 tokens):
    short sequences retire and free lanes that later arrivals fill, all
    transcripts stay bit-identical, and the engine drains clean."""
    rng = np.random.RandomState(3)
    workload = [(p, 1 + (i * 5) % 12)
                for i, p in enumerate(_prompts(rng, 12))]
    ref = _sequential_reference(_PARAMS, workload)
    eng = DecodeEngine(_PARAMS, **SPEC)
    try:
        streams = [eng.submit(p, n) for p, n in workload]
        got = [s.result(timeout=120) for s in streams]
        assert got == ref
        assert [len(g) for g in got] == [n for _, n in workload]
        assert eng.active_lanes() == 0 and eng.pending_depth() == 0
        assert eng.metrics.admitted.value >= len(workload)
        assert eng.metrics.retired.value == len(workload)
        assert eng.metrics.tokens.value == sum(n for _, n in workload)
    finally:
        eng.stop()


def test_submit_rejects_impossible_and_queue_full():
    eng = DecodeEngine(_PARAMS, **dict(SPEC, num_pages=8, max_pending=2,
                                       lane_buckets=(1,)))
    try:
        # 8 pages -> capacity 7 -> 28 tokens max; this can never fit
        with pytest.raises(MXNetError, match="never be admitted"):
            eng.submit(list(range(20)), 12)
        with pytest.raises(MXNetError, match="max_seq_len"):
            eng.submit([1, 2], S)
        # single lane + bounded queue: flood until QueueFullError
        accepted = [eng.submit([1, 2, 3], 8)]
        with pytest.raises(QueueFullError):
            for _ in range(8):
                accepted.append(eng.submit([1, 2, 3], 8))
        assert eng.metrics.rejected.value >= 1
        # backpressure, not deadlock: everything accepted still finishes
        for s in accepted:
            assert len(s.result(timeout=120)) == 8
    finally:
        eng.stop()


def test_pool_exhaustion_preempts_and_stays_bit_identical():
    """A pool too small for both long sequences at full length forces a
    mid-decode preemption (re-queue + re-prefill); greedy determinism
    makes the preempted stream's transcript identical anyway."""
    rng = np.random.RandomState(5)
    prompts = _prompts(rng, 2, lo=6, hi=7)
    workload = [(p, 14) for p in prompts]
    ref = _sequential_reference(_PARAMS, workload)
    # each seq peaks at 5 pages; capacity 7 cannot hold 2x5
    eng = DecodeEngine(_PARAMS, **dict(SPEC, num_pages=8,
                                       lane_buckets=(1, 2)))
    try:
        streams = [eng.submit(p, n) for p, n in workload]
        got = [s.result(timeout=120) for s in streams]
        assert got == ref
        assert eng.metrics.preempted.value >= 1
        assert eng.pool.free_pages() == eng.pool.capacity  # all freed
    finally:
        eng.stop()


def test_engine_contains_injected_step_fault():
    """A fault fired inside the decode loop fails the in-flight streams
    with the injected error but never wedges the engine: the next
    submit decodes normally."""
    eng = DecodeEngine(_PARAMS, **SPEC)
    try:
        ref = eng.generate([4, 8, 15], 5)
        with faults.inject("generation.engine.step:ioerr=1@#1"):
            stream = eng.submit([4, 8, 15], 5)
            with pytest.raises(IOError):
                stream.result(timeout=60)
        assert eng.generate([4, 8, 15], 5) == ref
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# recompile detector
# ---------------------------------------------------------------------------

def test_zero_recompiles_after_warmup():
    """Steady state never recompiles: a full mixed-length churn after
    warmup hits only warmed lane buckets and prefill buckets."""
    rng = np.random.RandomState(9)
    eng = DecodeEngine(_PARAMS, **SPEC)
    try:
        streams = [eng.submit(p, int(rng.randint(2, 9)))
                   for p in _prompts(rng, 10)]
        for s in streams:
            s.result(timeout=120)
        assert eng.cold_decode_runs() == 0
        assert set(SPEC["lane_buckets"]) <= eng.warmed_lane_buckets
        assert eng.metrics.cold_steps.value == 0
    finally:
        eng.stop()


def test_cold_decode_detector_fires_without_warmup():
    """The detector actually detects: with warmup skipped, the first
    decode steps hit never-warmed buckets and are counted."""
    eng = DecodeEngine(_PARAMS, warmup=False, **SPEC)
    try:
        eng.generate([1, 2, 3], 3)
        assert eng.cold_decode_runs() >= 1
    finally:
        eng.stop()


def test_telemetry_counters_render():
    eng = DecodeEngine(_PARAMS, **SPEC)
    try:
        eng.generate([2, 4, 6], 4)
        text = telemetry.render_prometheus()
        for name in ("mxtpu_gen_tokens_total",
                     "mxtpu_gen_sequences_admitted_total",
                     "mxtpu_gen_kv_pages_live", "mxtpu_gen_kv_pages_peak",
                     "mxtpu_gen_ttft_ms", "mxtpu_gen_itl_ms"):
            assert name in text, name
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# serving integration: server + HTTP streaming + router
# ---------------------------------------------------------------------------

def _server(**kw):
    return serving.InferenceServer(
        _NET, dict(_PARAMS), {"data": (2, S), "softmax_label": (2, S)},
        generator_spec=dict(SPEC), **kw)


def test_server_http_generate_streams_ndjson():
    srv = _server()
    try:
        prompt = [3, 11, 7]
        ref = srv.submit_generate(prompt, 8).result(timeout=60)
        host, port = srv.serve_http()
        req = urllib.request.Request(
            "http://%s:%d/generate" % (host, port),
            data=json.dumps({"prompt": prompt,
                             "max_new_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"})
        toks, done = [], None
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            for line in resp:
                obj = json.loads(line)
                if obj.get("done"):
                    done = obj
                    break
                toks.append(obj["token"])
        assert toks == ref
        assert done["n"] == len(ref) and done["ttft_ms"] > 0
    finally:
        srv.stop()


def test_http_generate_404_without_generator():
    srv = serving.InferenceServer(
        _NET, dict(_PARAMS), {"data": (2, S), "softmax_label": (2, S)})
    try:
        host, port = srv.serve_http()
        req = urllib.request.Request(
            "http://%s:%d/generate" % (host, port),
            data=json.dumps({"prompt": [1], "max_new_tokens": 2}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_router_generate_stream_parity():
    rng = np.random.RandomState(13)
    srvs = [_server() for _ in range(2)]
    router = serving.Router(srvs, seed=2)
    try:
        for p in _prompts(rng, 3):
            ref = _sequential_reference(_PARAMS, [(p, 7)])[0]
            assert list(router.generate(p, 7)) == ref
        snap = router.metrics.snapshot()
        assert snap["streams"].get("generate") == 3
    finally:
        router.close()
        for s in srvs:
            s.stop()


@pytest.mark.chaos
def test_router_resumes_stream_after_replica_kill():
    """Kill the replica actively decoding mid-stream: the Router
    re-submits prompt + tokens-so-far on a survivor and the client sees
    one uninterrupted, bit-identical token stream."""
    prompt = [5, 9, 2]
    ref = _sequential_reference(_PARAMS, [(prompt, 12)])[0]
    srvs = [_server() for _ in range(2)]
    router = serving.Router(srvs, seed=3)
    try:
        out, killed = [], False
        for tok in router.generate(prompt, 12):
            out.append(tok)
            if len(out) == 4 and not killed:
                killed = True
                victim = next(s for s in srvs
                              if s._generator.active_lanes() > 0)
                threading.Thread(target=victim.stop,
                                 kwargs={"drain": False}).start()
        assert out == ref
        assert router.metrics.snapshot()["stream_resumes"] >= 1
    finally:
        router.close()
        for s in srvs:
            s.stop()


# ---------------------------------------------------------------------------
# compile cache + AOT bundles
# ---------------------------------------------------------------------------

def _cc_reset():
    telemetry._reset_for_tests()
    cc.reset_stats()


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "cc")
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", d)
    _cc_reset()
    yield d
    _cc_reset()


def test_aot_bundle_roundtrips_decode_executables(cache_dir, tmp_path,
                                                  monkeypatch):
    """The generator's prefill/decode executables ride in the AOT bundle
    with their own cache kinds; from_checkpoint restores the generator
    from the warmup manifest and warms it deserialize-only."""
    spec = dict(SPEC, lane_buckets=(1, 2), prefill_len_buckets=(8,),
                prefill_batch_buckets=(1, 2))
    prefix = str(tmp_path / "gen")
    mx.model.save_checkpoint(prefix, 1, _NET, dict(_PARAMS), {})
    srv = serving.InferenceServer(
        _NET, dict(_PARAMS), {"data": (2, S), "softmax_label": (2, S)},
        generator_spec=spec)
    try:
        ref = srv.submit_generate([6, 3, 9], 5).result(timeout=60)
        kinds = {getattr(e, "_kind", None) for e in srv.compiled_entries()}
        assert "gen-step" in kinds and "gen-prefill" in kinds, kinds
        bundle = srv.save_aot_bundle(prefix, 1)
    finally:
        srv.stop()
    manifest = cc.read_manifest(bundle)
    assert manifest["warmup"]["generator"]["lane_buckets"] == [1, 2]

    # the admin CLI labels decode entries by kind
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools",
                                      "compile_cache_admin.py"),
         "ls", "--dir", cache_dir, "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    ls_kinds = {e.get("kind")
                for e in json.loads(out.stdout.strip().splitlines()[-1])}
    assert "gen-step" in ls_kinds and "gen-prefill" in ls_kinds, ls_kinds

    _cc_reset()
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", "")
    srv2 = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (2, S), "softmax_label": (2, S)})
    try:
        s = cc.stats()
        assert s["hits"] >= 1 and s["misses"] == 0, \
            "bundle-attached generator warmup still compiled: %s" % s
        assert srv2._generator is not None  # restored from the manifest
        assert srv2.submit_generate([6, 3, 9], 5).result(timeout=60) == ref
        assert srv2.cold_bucket_runs() == 0
    finally:
        srv2.stop()


# ---------------------------------------------------------------------------
# cross-request prefix caching + speculative decoding
# ---------------------------------------------------------------------------

def test_spec_greedy_bit_identical_across_k():
    """Speculative decoding with a draft model — here the target itself,
    but acceptance is argmax-vs-argmax so ANY draft works — must emit
    exactly the plain greedy transcript for every K: the verify graph is
    K+1 chained copies of the decode block, so accepted tokens are the
    target's own argmaxes by construction."""
    rng = np.random.RandomState(11)
    workload = [(p, int(rng.randint(3, 9))) for p in _prompts(rng, 5)]
    ref = _sequential_reference(_PARAMS, workload)
    for k in (1, 2, 3):
        eng = DecodeEngine(_PARAMS, draft={"params": dict(_PARAMS),
                                           "num_layers": LAYERS,
                                           "num_heads": HEADS,
                                           "hidden": HID, "k": k},
                           **SPEC)
        try:
            streams = [eng.submit(p, n) for p, n in workload]
            got = [s.result(timeout=120) for s in streams]
            proposed = sum(s.draft_proposed for s in streams)
            accepted = sum(s.draft_accepted for s in streams)
        finally:
            eng.stop()
        assert got == ref, "spec decode diverged at k=%d" % k
        assert eng.spec()["draft"]["k"] == k
        assert proposed > 0 and 0 < accepted <= proposed
    rendered = telemetry.render_prometheus()
    assert "mxtpu_gen_draft_proposed_total" in rendered
    assert "mxtpu_gen_draft_accepted_total" in rendered


def test_cached_prefix_admission_skips_prefill():
    """A request whose prompt the index fully covers admits with ZERO
    prefill steps and first token after ONE engine iteration — and the
    transcript still matches the uncached engine bit for bit."""
    rng = np.random.RandomState(13)
    shared = [int(t) for t in rng.randint(0, V, size=16)]
    spec = dict(SPEC, prefix_cache_pages=SPEC["num_pages"])
    ref = _sequential_reference(_PARAMS, [(shared, 6)])
    eng = DecodeEngine(_PARAMS, **spec)
    try:
        eng.generate(shared, 2, timeout=120)  # publishes the prefix
        st = eng.submit(shared, 6)
        got = st.result(timeout=120)
        assert got == ref[0]
        assert st.prefill_tokens == 0, \
            "cached admission still prefilled %d tokens" % st.prefill_tokens
        assert st.cached_prefix_tokens == len(shared) - 1
        assert st.ttft_iters == 1, st.ttft_iters
        snap = eng.pool.snapshot()
        assert snap["prefix_hits"] >= 1
    finally:
        eng.stop()
    rendered = telemetry.render_prometheus()
    assert "mxtpu_gen_prefix_hits_total" in rendered
    assert "mxtpu_gen_pages_shared" in rendered


def test_partial_prefix_hit_catches_up_in_one_iteration():
    """A 90%%-shared prompt (unique tail) admits against the index's
    page-granular match and batch-walks the remainder at admission:
    still zero prefill steps, still TTFT == 1 iteration, still
    bit-identical."""
    rng = np.random.RandomState(17)
    shared = [int(t) for t in rng.randint(0, V, size=18)]
    tail = [int(t) for t in rng.randint(0, V, size=3)]
    spec = dict(SPEC, prefix_cache_pages=SPEC["num_pages"])
    ref = _sequential_reference(_PARAMS, [(shared + tail, 5)])
    eng = DecodeEngine(_PARAMS, **spec)
    try:
        eng.generate(shared + [1], 2, timeout=120)
        st = eng.submit(shared + tail, 5)
        assert st.result(timeout=120) == ref[0]
        assert st.prefill_tokens == 0
        assert st.cached_prefix_tokens > 0
        assert st.ttft_iters == 1, st.ttft_iters
    finally:
        eng.stop()


def test_cow_isolation_never_mutates_shared_page():
    """Copy-on-write at the pool layer: a sequence diverging inside a
    shared page splits it first; the cached original — and any reader
    that mapped it — keeps its bytes."""
    rng = np.random.RandomState(19)
    pool = PagedKVPool(num_pages=16, page_size=4, num_layers=1,
                       num_heads=2, head_dim=4, prefix_cache_pages=8)
    t = [int(x) for x in rng.randint(0, V, size=8)]
    pages_a, cached = pool.alloc_prefix("a", 8, tokens=t)
    assert cached == 0  # cold index
    k = rng.randn(8, 2, 4).astype(np.float32)
    v = rng.randn(8, 2, 4).astype(np.float32)
    pool.write_prefill("a", 0, k, v, 8)
    assert pool.register_prefix("a", t) == 2  # both full pages published
    pool.free("a")  # refcount-0 pages retained as cache

    pages_b, cached_b = pool.alloc_prefix("b", 8, tokens=t)
    assert cached_b == 7  # capped at num_tokens - 1
    last = pages_b[1]
    assert pool.is_shared("b", 7)
    before = pool.k_pools[0][last].copy()

    assert pool.ensure_writable("b", 7)  # COW split
    row = pool.page_table_row("b", 4)
    assert int(row[1]) != last, "diverging seq still maps the shared page"
    pool.k_pools[0][int(row[1])][3] = 99.0  # b writes its own copy
    assert np.array_equal(pool.k_pools[0][last], before), \
        "COW leaked a write into the shared page"
    assert pool.snapshot()["cow_copies"] >= 1

    # a third request still hits the ORIGINAL bytes
    pages_c, cached_c = pool.alloc_prefix("c", 8, tokens=t)
    assert cached_c == 7 and pages_c[1] == last
    assert np.array_equal(pool.k_pools[0][last], before)
    pool.free("b")
    pool.free("c")
    assert pool.total_refcount() == 0


def test_preempted_lane_readmits_through_prefix_index():
    """Satellite regression: a preempted lane's re-admission consults
    the prefix index — prompt + generated-so-far re-enter as a cache
    hit, so the lane's prefill token count never grows past the
    original prompt."""
    rng = np.random.RandomState(23)
    prompt = [int(t) for t in rng.randint(0, V, size=9)]
    ref = _sequential_reference(_PARAMS, [(prompt, 6)])
    spec = dict(SPEC, prefix_cache_pages=SPEC["num_pages"])
    eng = DecodeEngine(_PARAMS, warmup=True, start=False, **spec)
    try:
        st = eng.submit(prompt, 6)
        eng._admit()
        assert st.prefill_tokens == len(prompt)
        eng._decode_step()  # a couple of tokens land before the preempt
        eng._decode_step()
        assert len(st.tokens) >= 2
        assert eng._preempt_one()
        eng._admit()  # re-admission: prefix HIT, not a second prefill
        assert st.prefill_tokens == len(prompt), \
            "re-admission re-prefilled the transcript"
        assert st.cached_prefix_tokens > 0
        assert eng.metrics.preempted.value == 1
        for _ in range(32):
            if st.done:
                break
            eng._decode_step()
        assert st.done and list(st.tokens) == ref[0]
    finally:
        eng.stop()


def test_aot_bundle_carries_draft_and_resolved_k(cache_dir, tmp_path,
                                                 monkeypatch):
    """The AOT bundle manifest carries the draft checkpoint (spilled to
    a sidecar .draft.params file) and the RESOLVED speculative K; a
    replica restored from the bundle speculates immediately with zero
    compiles and zero re-tuning."""
    spec = dict(SPEC, lane_buckets=(1, 2), prefill_len_buckets=(16,),
                prefill_batch_buckets=(1, 2),
                draft={"params": dict(_PARAMS), "num_layers": LAYERS,
                       "num_heads": HEADS, "hidden": HID, "k": 2})
    prefix = str(tmp_path / "gen")
    mx.model.save_checkpoint(prefix, 1, _NET, dict(_PARAMS), {})
    srv = serving.InferenceServer(
        _NET, dict(_PARAMS), {"data": (2, S), "softmax_label": (2, S)},
        generator_spec=spec)
    try:
        ref = srv.submit_generate([6, 3, 9], 5).result(timeout=120)
        assert srv._generator.spec()["draft"]["k"] == 2
        bundle = srv.save_aot_bundle(prefix, 1)
    finally:
        srv.stop()
    manifest = cc.read_manifest(bundle)
    gen_spec = manifest["warmup"]["generator"]
    assert gen_spec["draft"]["k"] == 2
    assert isinstance(gen_spec["draft"]["params"], str)
    assert gen_spec["draft"]["params"].endswith(".draft.params")
    assert os.path.exists(gen_spec["draft"]["params"])

    _cc_reset()
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", "")
    srv2 = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (2, S), "softmax_label": (2, S)})
    try:
        s = cc.stats()
        assert s["misses"] == 0, \
            "bundle-attached speculative rig still compiled: %s" % s
        eng2 = srv2._generator
        assert eng2 is not None and eng2.spec()["draft"]["k"] == 2
        st = srv2.submit_generate([6, 3, 9], 5)
        assert st.result(timeout=120) == ref
        assert st.draft_proposed > 0  # it actually speculated
        assert eng2.cold_decode_runs() == 0
    finally:
        srv2.stop()

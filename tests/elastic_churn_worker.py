"""Worker body for the membership-churn chaos scenario.

Spawned by ``tools/chaos_run.py --scenario membership-churn`` and by
``tests/test_elastic.py`` against a sync-mode kvstore server with
eviction enabled.  Every live worker pushes the SAME constant gradient
(ones * CHURN_GRAD) each step, so a flushed merge round applies exactly
``num_workers * CHURN_GRAD`` to the weight no matter how many workers
contributed: full rounds sum it directly, shrunken rounds are
renormalized by ``num_workers / len(round)`` server-side.  The final
weight is therefore ``CHURN_TOTAL_STEPS * num_workers * CHURN_GRAD``
independent of kill/evict/join timing — the reproducibility invariant
the churn test asserts.

Env contract (beyond the usual DMLC_* worker vars):

* ``CHURN_TOTAL_STEPS``  — rounds the job must complete (default 10).
* ``CHURN_JOIN_STEP``    — step at which survivors gate until the
  mid-run joiner shows up in the membership table (default 6); the
  joiner starts its own loop at this step.
* ``CHURN_EXPECT_MEMBERS`` — live-set size the gate waits for (default 3).
* ``CHURN_KILL_RANK`` / ``CHURN_FAULTS_SPEC`` / ``CHURN_FAULTS_SEED`` —
  the victim installs the seeded FaultPlan IN-PROCESS (only the matching
  rank, never a joiner): a plain ``MXNET_FAULTS_SPEC`` env would reach
  every worker with the same seed and kill them all.

Each worker prints one JSON line ``{rank, steps, final, target,
joiner}`` on success; the victim never gets there (the plan's ``kill``
is ``os._exit(137)``).
"""
import json
import os
import sys
import time


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)

    import mxnet_tpu as mx
    from mxnet_tpu import faults, kvstore

    rank = int(os.environ["DMLC_WORKER_ID"])
    is_joiner = os.environ.get("MXNET_KVSTORE_ELASTIC_JOIN") == "1"
    n_total = int(os.environ.get("CHURN_TOTAL_STEPS", "10"))
    j_sync = int(os.environ.get("CHURN_JOIN_STEP", "6"))
    expect = int(os.environ.get("CHURN_EXPECT_MEMBERS", "3"))
    grad_c = float(os.environ.get("CHURN_GRAD", "1.0"))
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))

    kill_rank = os.environ.get("CHURN_KILL_RANK")
    if kill_rank is not None and int(kill_rank) == rank and not is_joiner:
        faults.install(faults.FaultPlan(
            os.environ["CHURN_FAULTS_SPEC"],
            seed=int(os.environ.get("CHURN_FAULTS_SEED", "0"))))

    kv = kvstore.create("dist_async")
    kv.init("w", mx.nd.zeros((4,)))
    target = float(n_total * num_workers) * grad_c
    grad = mx.nd.ones((4,)) * grad_c
    out = mx.nd.zeros((4,))
    steps = 0
    for it in range(j_sync if is_joiner else 0, n_total):
        # the victim's seeded plan kills here (before the push: its
        # contribution to this round must never be half-sent)
        faults.fire("churn.worker.step")
        if not is_joiner and it == j_sync:
            # grow gate: wait for the mid-run joiner so post-join rounds
            # demonstrably count the full live set
            deadline = time.monotonic() + 60.0
            while len(kv.membership()["ranks"]) < expect:
                if time.monotonic() > deadline:
                    print(json.dumps({"rank": rank,
                                      "error": "joiner never arrived"}),
                          flush=True)
                    sys.exit(4)
                time.sleep(0.05)
        kv.push("w", grad)
        kv._barrier()
        steps += 1
    if is_joiner:
        # leave right away: the survivors' last round may still be
        # waiting on this member, and our departure is what flushes it
        kv.pull("w", out)
        final = float(out.asnumpy()[0])
    else:
        # rounds flush as stragglers leave; poll until the invariant
        # value lands (bounded, so a real stall still fails the test)
        deadline = time.monotonic() + 60.0
        while True:
            kv.pull("w", out)
            final = float(out.asnumpy()[0])
            if final >= target - 1e-6 or time.monotonic() > deadline:
                break
            time.sleep(0.05)
    print(json.dumps({"rank": rank, "steps": steps, "final": final,
                      "target": target, "joiner": is_joiner}), flush=True)
    kv.close()


if __name__ == "__main__":
    main()

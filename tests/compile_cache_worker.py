"""Subprocess worker for the compile-cache cross-process tests.

One process = one cold start.  Builds a deterministic tiny model, runs it
(a Predictor forward or a short fused-step training run), and prints ONE
json line: ``{"digest": ..., "stats": compile_cache.stats()}``.

The parent runs this twice against one ``MXNET_COMPILE_CACHE_DIR``:
process A must compile-and-store (misses > 0), process B must start warm
(hits > 0, misses == 0) and produce a bit-identical ``digest`` — the
executable it deserialized stands in for the one A compiled.

Usage: python tests/compile_cache_worker.py {predict|train}
       (cache dir comes from MXNET_COMPILE_CACHE_DIR; empty = cache off)
"""
import hashlib
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

D, HID, K, BATCH = 6, 8, 3, 8


def _mlp():
    import mxnet_tpu as mx

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=HID,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=K, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(seed=5):
    import numpy as np

    rng = np.random.RandomState(seed)
    return {
        "fc1_weight": rng.randn(HID, D).astype(np.float32) * 0.3,
        "fc1_bias": np.zeros(HID, np.float32),
        "fc2_weight": rng.randn(K, HID).astype(np.float32) * 0.3,
        "fc2_bias": np.zeros(K, np.float32),
    }


def run_predict():
    import numpy as np
    import mxnet_tpu as mx

    pred = mx.Predictor(_mlp(), {k: mx.nd.array(v)
                                 for k, v in _params().items()},
                        {"data": (2, D)})
    X = np.linspace(-1.0, 1.0, 2 * D, dtype=np.float32).reshape(2, D)
    out = pred.forward(data=X)[0].asnumpy()
    return hashlib.sha256(np.ascontiguousarray(out).tobytes()).hexdigest()


def run_train():
    import numpy as np
    import mxnet_tpu as mx

    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (BATCH, D))],
             label_shapes=[("softmax_label", (BATCH,))])
    arg_params = {k: mx.nd.array(v) for k, v in _params().items()}
    mod.init_params(arg_params=arg_params, aux_params={},
                    allow_missing=False)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(11)
    for _ in range(3):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rng.randn(BATCH, D).astype(np.float32))],
            label=[mx.nd.array(
                rng.randint(0, K, size=BATCH).astype(np.float32))])
        mod.forward_backward(batch)
        mod.update()
    final, _ = mod.get_params()
    h = hashlib.sha256()
    for name in sorted(final):
        h.update(name.encode())
        h.update(np.ascontiguousarray(final[name].asnumpy()).tobytes())
    return h.hexdigest()


def main(argv=None):
    mode = (argv or sys.argv[1:])[0]
    from mxnet_tpu import compile_cache

    digest = {"predict": run_predict, "train": run_train}[mode]()
    print(json.dumps({"digest": digest, "stats": compile_cache.stats()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())

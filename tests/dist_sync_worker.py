"""Worker body for the dist_sync test (launched by tools/launch.py with 4
processes).  Asserts the analytically-known sync-sum across workers — the
repo's version of /root/reference/tests/nightly/dist_sync_kvstore.py:30-44,
including a big key exercising larger payloads."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402


def main():
    kv = mx.kvstore.create("dist_sync")
    nworker = kv.num_workers
    rank = kv.rank
    assert nworker == int(os.environ["DMLC_NUM_WORKER"]), \
        (nworker, os.environ["DMLC_NUM_WORKER"])

    shape = (3, 3)
    big_shape = (100, 100)

    # ---- raw sync-sum (no updater): every pull sees the all-worker sum
    kv.init(3, mx.nd.ones(shape))
    kv.init(99, mx.nd.ones(big_shape))
    for it in range(3):
        kv.push(3, mx.nd.ones(shape) * (rank + 1))
        kv.push(99, mx.nd.ones(big_shape) * (rank + 2))
        out = mx.nd.zeros(shape)
        big = mx.nd.zeros(big_shape)
        kv.pull(3, out=out)
        kv.pull(99, out=big)
        expect = sum(r + 1 for r in range(nworker))
        expect_big = sum(r + 2 for r in range(nworker))
        np.testing.assert_allclose(out.asnumpy(),
                                   np.full(shape, expect, np.float32))
        np.testing.assert_allclose(big.asnumpy(),
                                   np.full(big_shape, expect_big, np.float32))

    # ---- init broadcast: non-root inits are overridden by rank 0's value
    kv.init(7, mx.nd.ones(shape) * (1.0 if rank == 0 else 555.0))
    out = mx.nd.zeros(shape)
    kv.pull(7, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(shape, np.float32))

    # ---- updater path: identical deterministic update on every worker
    kv.set_optimizer(mx.optimizer.Test(rescale_grad=1.0))
    kv.init(11, mx.nd.zeros(shape))
    for it in range(2):
        kv.push(11, mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.zeros(shape)
    kv.pull(11, out=out)
    expect = 2 * sum(r + 1 for r in range(nworker))  # Test: w += sum(grad)
    np.testing.assert_allclose(out.asnumpy(),
                               np.full(shape, expect, np.float32))

    kv._barrier()
    print("dist_sync_worker %d/%d OK" % (rank, nworker), flush=True)


if __name__ == "__main__":
    main()

"""mxnet_tpu.platform tests — placement planning, model paging over AOT
bundles, per-tenant quotas, and the multi-model front door.  All CPU-only:
device pools are tiny explicit budgets and planner capacity runs off the
specs' declared ``param_bytes``, so the packing math is deterministic and
independent of real checkpoint sizes."""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.models.dlrm import get_dlrm
from mxnet_tpu.models.resnet import get_resnet
from mxnet_tpu.platform import (DevicePool, FrontDoor, ModelManager,
                                ModelSpec, PlacementPlanner,
                                TenantQuotaExceededError, TenantQuotas)
from mxnet_tpu.serving.registry import ReplicaRegistry
from mxnet_tpu.serving.router import Router

IN_DIM = 4
V, LAYERS, HEADS, HID, S = 32, 1, 2, 16, 16
LM_SPEC = dict(vocab_size=V, num_layers=LAYERS, num_heads=HEADS, hidden=HID,
               max_seq_len=S, lane_buckets=(1,), page_size=4, num_pages=16,
               prefill_len_buckets=(8,), prefill_batch_buckets=(1,))


@pytest.fixture(autouse=True)
def _platform_env(tmp_path, monkeypatch):
    """Fresh compile cache per test + no anti-thrash guard, so replans
    actuate immediately and bundles never leak across tests."""
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    monkeypatch.setenv("MXNET_PLATFORM_MIN_RESIDENT_S", "0")
    telemetry._reset_for_tests()
    yield
    telemetry._reset_for_tests()


# -- checkpoint builders -----------------------------------------------------

def _save_fc(tmp_path, name, seed=0, in_dim=IN_DIM, hid=2):
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=hid,
                                name="fc")
    params = {
        "fc_weight": mx.nd.array(rng.randn(hid, in_dim).astype(np.float32)),
        "fc_bias": mx.nd.array(rng.randn(hid).astype(np.float32)),
    }
    prefix = str(tmp_path / name)
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    return prefix, {"data": (1, in_dim)}


def _save_resnet(tmp_path, name):
    net = get_resnet(num_classes=4, num_layers=18, image_shape=(1, 8, 8))
    arg_shapes, _, aux_shapes = net.infer_shape(data=(1, 1, 8, 8),
                                                softmax_label=(1,))
    rng = np.random.RandomState(0)
    args = {n: mx.nd.array(rng.uniform(-0.05, 0.05, s).astype(np.float32))
            for n, s in zip(net.list_arguments(), arg_shapes)
            if n != "data" and not n.endswith("_label")}
    auxs = {n: mx.nd.array((np.zeros if n.endswith("mean") else np.ones)
                           (s, np.float32))
            for n, s in zip(net.list_auxiliary_states(), aux_shapes)}
    prefix = str(tmp_path / name)
    mx.model.save_checkpoint(prefix, 1, net, args, auxs)
    return prefix, {"data": (1, 1, 8, 8)}


def _save_dlrm(tmp_path, name):
    net, _slots = get_dlrm(num_slots=2, vocab_sizes=[16, 16], embed_dim=4,
                           capacity=16, bag_len=2, dense_dim=4,
                           bottom_hidden=(8,), top_hidden=(8,))
    shapes = {"dense": (1, 4), "slot0_indices": (1, 2),
              "slot1_indices": (1, 2)}
    arg_shapes, _, _ = net.infer_shape(
        dense=(1, 4), slot0_indices=(1, 2), slot1_indices=(1, 2),
        ctr_label=(1,))
    rng = np.random.RandomState(1)
    params = {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n not in shapes and not n.endswith("_label")}
    prefix = str(tmp_path / name)
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    return prefix, shapes


def _save_lm(tmp_path, name):
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=LAYERS,
                                       num_heads=HEADS, hidden=HID,
                                       seq_len=S)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(2)
    params = {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n not in ("data", "softmax_label")}
    prefix = str(tmp_path / name)
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    return prefix, {"data": (1, S), "softmax_label": (1, S)}


def _fc_spec(tmp_path, name, **kw):
    prefix, shapes = _save_fc(tmp_path, name, seed=sum(map(ord, name)) % 97)
    kw.setdefault("param_bytes", 1000)
    kw.setdefault("server_kwargs", {"buckets": (1,)})
    return ModelSpec(name, prefix, 1, shapes, **kw)


# -- planner unit tests ------------------------------------------------------

def _spec(name, pbytes=100, **kw):
    """A planner-only spec: no checkpoint on disk, explicit footprint.
    With the default 0.25 exec overhead the total is pbytes * 1.25."""
    return ModelSpec(name, "/nonexistent/%s" % name, 1,
                     {"data": (1, IN_DIM)}, param_bytes=pbytes, **kw)


def test_spec_validation_and_footprint():
    with pytest.raises(mx.MXNetError):
        ModelSpec("", "p", 1, {})
    with pytest.raises(mx.MXNetError):
        ModelSpec("a/b", "p", 1, {})
    with pytest.raises(mx.MXNetError):
        ModelSpec("m", "p", 1, {}, slo="gold")
    s = _spec("m", pbytes=100)
    assert s.footprint() == {"param_bytes": 100, "kv_bytes": 0,
                             "exec_bytes": 25, "total": 125}
    # a generator spec implies a paged KV pool:
    # 2 (K+V) * layers * pages * page_size * heads * head_dim * 4B
    g = _spec("g", pbytes=100, slo="generate",
              generator_spec=dict(num_layers=1, num_heads=2, hidden=8,
                                  page_size=4, num_pages=2))
    assert g.footprint()["kv_bytes"] == 2 * 1 * 2 * 4 * 2 * 4 * 4
    # live measurement overrides the exec-overhead estimate
    s.observe_exec_bytes(7)
    assert s.footprint()["exec_bytes"] == 7


def test_planner_packs_by_demand():
    """10 models, room for 4: the highest-demand models win residency,
    the rest are planned paged."""
    pool = DevicePool(num_devices=1, bytes_per_device=510)
    specs = {("m%d" % i): _spec("m%d" % i) for i in range(10)}  # 125 each
    demand = {"m2": 9.0, "m5": 8.0, "m7": 7.0, "m0": 6.0, "m1": 0.1}
    plan = PlacementPlanner(pool).plan(specs, demand)
    assert sorted(plan.resident) == ["m0", "m2", "m5", "m7"]
    assert len(plan.paged) == 6
    assert all(a["op"] == "fault_in" for a in plan.actions)
    assert plan.free_bytes[0] == 510 - 4 * 125


def test_planner_slo_breaks_demand_ties():
    pool = DevicePool(num_devices=1, bytes_per_device=130)
    specs = {"b": _spec("b", slo="batch"), "i": _spec("i")}
    plan = PlacementPlanner(pool).plan(specs, {"b": 1.0, "i": 1.0})
    assert plan.resident == {"i": 0} and plan.paged == ["b"]


def test_planner_sticky_placement_and_action_diff():
    pool = DevicePool(num_devices=2, bytes_per_device=300)
    specs = {n: _spec(n) for n in ("a", "b", "c")}
    demand = {"a": 3.0, "b": 2.0, "c": 1.0}
    # 'b' currently sits on device 1; both devices fit it, so it stays
    plan = PlacementPlanner(pool).plan(specs, demand,
                                       current={"b": 1, "gone": 0})
    assert plan.resident["b"] == 1
    ops = {a["op"] for a in plan.actions}
    assert {"op": "page_out", "model": "gone", "device": 0} \
        in plan.actions
    assert "fault_in" in ops and "page_out" in ops


def test_planner_rejects_model_larger_than_any_device():
    pool = DevicePool(num_devices=2, bytes_per_device=100)
    with pytest.raises(mx.MXNetError):
        PlacementPlanner(pool).plan({"big": _spec("big", pbytes=200)}, {})


# -- quota unit tests --------------------------------------------------------

def test_quota_rate_limit_sheds_only_the_offender():
    q = TenantQuotas(pressure_fn=lambda: 0.0)
    q.set_quota("noisy", rate=1.0, burst=1.0)
    q.set_quota("good", rate=1000.0, burst=1000.0)
    shed = 0
    for _ in range(5):
        try:
            q.admit("noisy")
        except TenantQuotaExceededError as exc:
            assert exc.retry_after > 0
            shed += 1
    assert shed >= 3  # burst=1: one admit, then the bucket is dry
    for _ in range(5):
        q.admit("good")  # neighbour never sheds
    snap = q.snapshot()
    assert snap["noisy"]["shed"] == shed
    assert snap["good"]["shed"] == 0 and snap["good"]["admitted"] == 5


def test_quota_fair_share_sheds_heavy_tenant_under_pressure():
    pressure = [0.0]
    q = TenantQuotas(pressure_fn=lambda: pressure[0])
    q.set_quota("heavy", weight=1.0)
    q.set_quota("light", weight=1.0)
    # build magnitude-different EWMA rates while the fleet is calm
    for _ in range(200):
        q.admit("heavy")
    for _ in range(5):
        q.admit("light")
        time.sleep(0.05)
    pressure[0] = 1.0  # fleet saturates: fair sharing engages
    with pytest.raises(TenantQuotaExceededError):
        for _ in range(50):
            q.admit("heavy")
    q.admit("light")  # inside its share: never shed by the neighbour


# -- registry meta + model-scoped routers ------------------------------------

def _tiny_server(seed=0):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="fc")
    rng = np.random.RandomState(seed)
    params = {"fc_weight": mx.nd.array(rng.randn(2, IN_DIM)
                                       .astype(np.float32)),
              "fc_bias": mx.nd.array(rng.randn(2).astype(np.float32))}
    return serving.InferenceServer(net, params, {"data": (1, IN_DIM)},
                                   buckets=(1,), warmup=False)


def test_registry_meta_and_model_scoped_router_views():
    """One shared registry, N model-scoped router views: meta carries the
    model label; members registered without meta stay visible to legacy
    (unscoped) routers and count as model 'default'."""
    reg = ReplicaRegistry(ttl_ms=60_000)
    sa, sb, sc = _tiny_server(0), _tiny_server(1), _tiny_server(2)
    try:
        reg.register("a/r1", sa, meta={"model": "a", "tenant": "t0"})
        reg.register("b/r1", sb, meta={"model": "b"})
        reg.register("legacy", sc)  # pre-meta wire format
        live = reg.live()
        assert live["meta"]["a/r1"] == {"model": "a", "tenant": "t0"}
        assert live["meta"]["legacy"] == {}

        ra = Router(registry=reg, model="a", registry_sync_ms=10_000)
        rb = Router(registry=reg, model="b", registry_sync_ms=10_000)
        rall = Router(registry=reg, registry_sync_ms=10_000)
        rdef = Router(registry=reg, model="default",
                      registry_sync_ms=10_000)
        try:
            assert [r.name for r in ra.replicas()] == ["a/r1"]
            assert [r.name for r in rb.replicas()] == ["b/r1"]
            assert len(rall.replicas()) == 3  # unscoped sees everything
            assert [r.name for r in rdef.replicas()] == ["legacy"]

            out = ra.submit(data=np.zeros(IN_DIM, np.float32)).result()
            assert np.asarray(out[0]).shape == (2,)

            # deregistration propagates through the scoped view
            reg.deregister("a/r1")
            ra.sync_registry()
            assert ra.replicas() == []
        finally:
            ra.close()
            rb.close()
            rall.close()
            rdef.close()
    finally:
        reg.close()
        for s in (sa, sb, sc):
            s.stop(drain=False)


# -- manager: paging lifecycle ----------------------------------------------

def test_manager_fault_in_page_out_releases_memory(tmp_path):
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr:
        mgr.register_model(_fc_spec(tmp_path, "solo"))
        with pytest.raises(mx.MXNetError):
            mgr.register_model(_fc_spec(tmp_path, "solo"))  # dup name
        with pytest.raises(mx.MXNetError):
            mgr.spec("nope")

        srv = mgr.fault_in("solo")
        assert mgr.fault_in("solo") is srv  # idempotent
        out = srv.submit(data=np.zeros(IN_DIM, np.float32)).result()
        assert np.asarray(out[0]).shape == (2,)
        assert mgr.resident_bytes() > 0
        assert mgr.registry.live()["meta"]["solo/r1"]["model"] == "solo"
        assert mgr.fault_in_latency_ms("solo") > 0

        mgr.page_out("solo")
        assert mgr.resident_bytes() == 0
        assert mgr.server_for("solo") is None
        assert mgr.registry.live()["replicas"] == {}
        mgr.page_out("solo")  # no-op on non-resident

        # the page-out left an AOT bundle: the next fault-in is warm
        srv2 = mgr.fault_in("solo")
        srv2.submit(data=np.zeros(IN_DIM, np.float32)).result()
        assert srv2.cold_bucket_runs() == 0
    assert mgr.server_for("solo") is None  # close() pages everything out


def test_platform_metrics_render(tmp_path):
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr:
        mgr.register_model(_fc_spec(tmp_path, "m0"))
        mgr.fault_in("m0")
        mgr.page_out("m0")
        text = telemetry.render_prometheus()
    assert 'mxtpu_platform_fault_ins_total{model="m0"} 1' in text
    assert 'mxtpu_platform_page_outs_total{model="m0"} 1' in text
    assert "mxtpu_platform_registered_models 1" in text
    assert "mxtpu_platform_resident_models 0" in text


# -- the acceptance path -----------------------------------------------------

def test_platform_acceptance_ten_models_room_for_four(tmp_path,
                                                      monkeypatch):
    """The ISSUE's acceptance scenario: 10 heterogeneous models (ResNet
    classifier, DLRM, transformer-LM generator, 7 FC nets) registered on
    a pool with room for ~4.  Demand decides residency; requests for
    paged models fault them in warm (zero cold-bucket runs once a bundle
    exists); page-outs provably release device memory; a flooding tenant
    is shed without touching its neighbours."""
    # pin the declared footprints: live cost-analysis refinement would
    # re-scale the toy byte budget mid-test and make packing math racy
    monkeypatch.setattr(ModelSpec, "observe_exec_bytes",
                        lambda self, nbytes: None)
    rn_prefix, rn_shapes = _save_resnet(tmp_path, "rn")
    dl_prefix, dl_shapes = _save_dlrm(tmp_path, "dlrm")
    lm_prefix, lm_shapes = _save_lm(tmp_path, "lm")

    # every spec declares the SAME total footprint (the lm's KV pool
    # counts toward its total, so its declared params are smaller) —
    # capacity for 4 means capacity for exactly 4, whatever the mix
    specs = [
        ModelSpec("resnet", rn_prefix, 1, rn_shapes, tenant="vision",
                  slo="interactive", param_bytes=7554,
                  server_kwargs={"buckets": (1,)}),
        ModelSpec("dlrm", dl_prefix, 1, dl_shapes, tenant="ads",
                  slo="interactive", param_bytes=7554,
                  server_kwargs={"buckets": (1,)}),
        ModelSpec("lm", lm_prefix, 1, lm_shapes, tenant="chat",
                  slo="generate", param_bytes=1000,
                  generator_spec=dict(LM_SPEC),
                  server_kwargs={"buckets": (1,)}),
    ]
    for i in range(7):
        specs.append(_fc_spec(tmp_path, "fc%d" % i, param_bytes=7554,
                              tenant="t%d" % (i % 3),
                              slo="batch" if i >= 5 else "interactive"))
    totals = {s.footprint()["total"] for s in specs}
    assert len(totals) == 1, totals  # equal-footprint premise
    first_four = {"resnet", "dlrm", "lm", "fc0"}
    pool = DevicePool(num_devices=1, bytes_per_device=4 * totals.pop() + 1)

    with ModelManager(pool) as mgr, FrontDoor(mgr) as door:
        for s in specs:
            mgr.register_model(s)
        assert len(mgr.models()) == 10

        for name, d in (("resnet", 9), ("dlrm", 8), ("lm", 7), ("fc0", 6)):
            mgr.record_demand(name, d)
        plan = mgr.replan()
        assert set(plan.resident) == first_four
        assert len(plan.paged) == 6
        assert set(mgr.placement()) == first_four

        # serve every resident model through the front door (per-item
        # inputs: the batch axis is the server's, not the caller's)
        r = door.predict("resnet", tenant="vision",
                         data=np.zeros((1, 8, 8), np.float32))
        assert np.asarray(r[0]).shape == (4,)
        r = door.predict("dlrm", tenant="ads",
                         dense=np.zeros(4, np.float32),
                         slot0_indices=np.zeros(2, np.float32),
                         slot1_indices=np.zeros(2, np.float32))
        assert np.asarray(r[0]).shape == (1,)
        toks = list(door.generate("lm", [3, 1, 4], 4, tenant="chat"))
        assert len(toks) == 4 and all(0 <= t < V for t in toks)
        door.predict("fc0", data=np.zeros(IN_DIM, np.float32))

        bytes_at_peak = mgr.resident_bytes()
        assert bytes_at_peak > 0

        # diurnal shift: demand moves to fc1..fc4 — the first four page
        # out (writing AOT bundles), the new four fault in
        for name in first_four:
            mgr.record_demand(name, -mgr.demand()[name])
        for i, d in zip(range(1, 5), (9, 8, 7, 6)):
            mgr.record_demand("fc%d" % i, d)
        plan = mgr.replan()
        assert set(plan.resident) == {"fc1", "fc2", "fc3", "fc4"}
        assert "resnet" in plan.paged and "lm" in plan.paged
        assert mgr.resident_bytes() < bytes_at_peak
        door.predict("fc3", data=np.zeros(IN_DIM, np.float32))

        # demand paging through the front door: a request for the now
        # paged-out fc0 faults it back in WARM from its bundle
        door.predict("fc0", data=np.zeros(IN_DIM, np.float32))
        srv = mgr.server_for("fc0")
        assert srv is not None
        assert srv.cold_bucket_runs() == 0  # bundle-warmed: no compiles
        metas = mgr.registry.live()["meta"]
        assert any(m.get("model") == "fc0" for m in metas.values())

        # tenant isolation: 'noisy' floods past its quota and is 429d;
        # 'vision' keeps its SLO untouched
        door.quotas.set_quota("noisy", rate=1.0, burst=2.0)
        sheds = 0
        for _ in range(8):
            try:
                door.predict("fc1", tenant="noisy",
                             data=np.zeros(IN_DIM, np.float32))
            except TenantQuotaExceededError:
                sheds += 1
        assert sheds >= 5
        r = door.predict("resnet", tenant="vision",
                         data=np.zeros((1, 8, 8), np.float32))
        assert np.asarray(r[0]).shape == (4,)
        snap = door.quotas.snapshot()
        assert snap["noisy"]["shed"] == sheds
        assert snap["vision"]["shed"] == 0

        d = door.describe()
        assert set(d["models"]) == set(mgr.models())
        # fc0 and resnet were demand-paged back in by the requests
        # above; lm saw no traffic since the shift and stays paged
        assert "fc0" in d["resident"] and "resnet" in d["resident"]
        assert "lm" in d["paged"]


def test_frontdoor_http_multi_model(tmp_path):
    """The HTTP face: model from the path or header, tenant from
    X-Tenant, 429 + Retry-After for the offending tenant only."""
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr, FrontDoor(mgr) as door:
        mgr.register_model(_fc_spec(tmp_path, "alpha"))
        mgr.register_model(_fc_spec(tmp_path, "beta"))
        door.quotas.set_quota("noisy", rate=0.5, burst=1.0)
        host, port = door.serve_http()
        base = "http://%s:%d" % (host, port)

        def post(path, body, headers=()):
            req = urllib.request.Request(
                base + path, data=json.dumps(body).encode(),
                headers=dict({"Content-Type": "application/json"},
                             **dict(headers)), method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, json.loads(resp.read())

        x = {"inputs": {"data": [0.0] * IN_DIM}}
        code, out = post("/v1/alpha/predict", x)
        assert code == 200 and np.asarray(out["outputs"][0]).shape == (2,)
        code, out = post("/predict", x, [("X-MXNet-Model", "beta")])
        assert code == 200

        # flood from 'noisy': the second request trips its token bucket
        post("/v1/alpha/predict", x, [("X-Tenant", "noisy")])
        with pytest.raises(urllib.error.HTTPError) as ei:
            for _ in range(3):
                post("/v1/alpha/predict", x, [("X-Tenant", "noisy")])
        assert ei.value.code == 429
        assert float(ei.value.headers["Retry-After"]) > 0

        with urllib.request.urlopen(base + "/models", timeout=10) as resp:
            cat = json.loads(resp.read())
        assert set(cat["models"]) == {"alpha", "beta"}
        assert "noisy" in cat["tenants"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/v1/ghost/predict", x)
        assert ei.value.code == 400  # unknown model

        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert 'mxtpu_platform_fault_ins_total{model="alpha"}' in text

"""Training guardian: numeric-anomaly detection, graded response
(skip -> LR re-warm -> rollback), last-good retention ring, the fused
on-device step guard, and the kvstore server's non-finite push NACK.

The end-to-end rollback-and-replay bit-identity proof lives in
tests/test_chaos.py (sdc-rollback) so it rides the chaos marker; this
file covers the units and the cheap integration seams.
"""
import os
import threading
import time
import timeit

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, guardian
from mxnet_tpu import kvstore_server as kvs


@pytest.fixture(autouse=True)
def _guardian_clean():
    """Every test starts and ends with the guardian off and zeroed."""
    guardian.disable()
    guardian.reset_stats()
    yield
    faults.uninstall()
    guardian.disable()
    guardian.reset_stats()


def _fake_clock(start=100.0):
    state = {"t": start}

    def clock():
        state["t"] += 1.0
        return state["t"]

    return clock


# ---------------------------------------------------------------------------
# the ladder (pure unit, fake clock)
# ---------------------------------------------------------------------------
def test_ladder_skip_rewarm_rollback_sequence():
    g = guardian.Guardian(clock=_fake_clock(), skip_max=2, rewarm_steps=10,
                          rewarm_factor=0.1, rollback_max=5, warmup=4)
    actions = [g.observe(finite=False) for _ in range(6)]
    # consec 1..2 skip, 3 starts the re-warm rung, 4..5 skip under the
    # fresh ramp, 6 exhausts the ladder
    assert actions == ["skip", "skip", "rewarm", "skip", "skip", "rollback"]


def test_clean_step_resets_the_ladder():
    g = guardian.Guardian(clock=_fake_clock(), skip_max=1, rewarm_steps=0,
                          warmup=2)
    assert g.observe(finite=False) == "skip"
    assert g.observe(finite=True, gnorm=1.0) == "ok"
    # consecutive count reset: the next anomaly is a fresh skip, not an
    # escalation
    assert g.observe(finite=False) == "skip"
    assert g.observe(finite=False) == "rollback"  # rewarm rung removed


def test_immediate_rollback_when_skip_and_rewarm_disabled():
    g = guardian.Guardian(clock=_fake_clock(), skip_max=0, rewarm_steps=0)
    assert g.observe(finite=False) == "rollback"


def test_nonfinite_gnorm_or_loss_is_an_anomaly():
    g = guardian.Guardian(clock=_fake_clock(), skip_max=1, warmup=2)
    assert g.observe(finite=True, gnorm=float("inf")) == "skip"
    g2 = guardian.Guardian(clock=_fake_clock(), skip_max=1, warmup=2)
    assert g2.observe(finite=True, gnorm=1.0, loss=float("nan")) == "skip"


def test_spike_detector_arms_after_warmup():
    g = guardian.Guardian(clock=_fake_clock(), skip_max=3, warmup=4,
                          spike_mult=10.0, spike_window=8)
    # before warmup history exists even a huge norm passes
    assert g.observe(finite=True, gnorm=1000.0) == "ok"
    for _ in range(4):
        assert g.observe(finite=True, gnorm=1.0) == "ok"
    # 1000 > 10x the rolling median -> grad_spike anomaly
    assert g.observe(finite=True, gnorm=1000.0) == "skip"
    # a clean value still passes and the spike was NOT added to history
    assert g.observe(finite=True, gnorm=2.0) == "ok"
    st = guardian.stats()
    assert st["anomalies"] == 1 and st["skips"] == 1


def test_rewarm_ramp_multiplier_and_governor():
    g = guardian.Guardian(clock=_fake_clock(), skip_max=0, rewarm_steps=4,
                          rewarm_factor=0.25, rollback_max=5, warmup=2)
    assert g.lr_mult() == 1.0
    assert guardian.current_lr_mult() == 1.0
    assert g.observe(finite=False) == "rewarm"
    assert g.lr_mult() == pytest.approx(0.25)
    # the module-global governor now points at this ramp
    assert guardian.current_lr_mult() == pytest.approx(0.25)
    mults = []
    for _ in range(4):
        assert g.observe(finite=True, gnorm=1.0) == "ok"
        mults.append(g.lr_mult())
    assert mults == sorted(mults)  # monotone ramp up
    assert mults[-1] == pytest.approx(1.0)
    assert guardian.current_lr_mult() == 1.0  # governor released


def test_rollback_budget_exhaustion_raises():
    g = guardian.Guardian(clock=_fake_clock(), rollback_max=2)
    g.note_rollback(to_step=0)
    g.note_rollback(to_step=0)
    with pytest.raises(guardian.GuardianAbort):
        g.note_rollback(to_step=0)
    assert guardian.stats()["rollbacks"] == 3


# ---------------------------------------------------------------------------
# the last-good retention ring
# ---------------------------------------------------------------------------
def test_snapshot_ring_retention_and_dedupe():
    g = guardian.Guardian(clock=_fake_clock(), ring=2, snapshot_every=2,
                          skip_max=2, warmup=2)
    calls = []

    def capture_at(tag):
        def capture():
            calls.append(tag)
            return {"tag": tag}
        return capture

    assert g.snapshot_due()  # step 0 always qualifies
    assert g.offer_snapshot(capture_at("s0"))
    # same step again (a path that never observes): refused, captured once
    assert not g.offer_snapshot(capture_at("dup"))
    assert calls == ["s0"]

    g.observe(finite=True, gnorm=1.0)  # step 1
    assert not g.offer_snapshot(capture_at("odd"))  # not due, no force
    assert g.offer_snapshot(capture_at("forced"), force=True)
    g.observe(finite=True, gnorm=1.0)  # step 2
    assert g.offer_snapshot(capture_at("s2"))
    # ring keeps the newest 2 of the 3 retained
    assert [s for s, _ in g._ring] == [1, 2]
    assert g.rollback_target()[1]["tag"] == "s2"
    assert guardian.stats()["snapshots"] == 3


def test_snapshot_refused_while_anomalies_live():
    g = guardian.Guardian(clock=_fake_clock(), ring=2, snapshot_every=1,
                          skip_max=5, warmup=2)
    g.observe(finite=False)  # live anomaly
    assert not g.offer_snapshot(lambda: {"bad": True}, force=True)


def test_rollback_target_match_filter():
    g = guardian.Guardian(clock=_fake_clock(), ring=4, snapshot_every=1,
                          warmup=2)
    g.offer_snapshot(lambda: {"epoch": 0})
    g.observe(finite=True, gnorm=1.0)
    g.offer_snapshot(lambda: {"epoch": 1})
    step, snap = g.rollback_target(lambda s: s["epoch"] == 0)
    assert (step, snap["epoch"]) == (0, 0)
    assert g.rollback_target(lambda s: s["epoch"] == 9) is None
    assert g.rollback_target()[1]["epoch"] == 1


# ---------------------------------------------------------------------------
# module integration: the step guard gates poisoned updates out
# ---------------------------------------------------------------------------
def _small_module(fused):
    os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
    try:
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=[("data", (4, 6))],
                 label_shapes=[("softmax_label", (4,))])
        mx.random.seed(0)
        np.random.seed(0)
        mod.init_params(initializer=mx.init.Xavier(), force_init=True)
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1},
                           force_init=True)
    finally:
        os.environ.pop("MXNET_FUSED_STEP", None)
    return mod


def _step(mod, x):
    y = mx.nd.array(np.zeros(4, dtype=np.float32))
    mod.forward_backward(mx.io.DataBatch(data=[mx.nd.array(x)], label=[y],
                                         pad=0))
    mod.update()


@pytest.mark.parametrize("fused", [True, False])
def test_step_guard_skips_poisoned_update(fused):
    guardian.enable()
    mod = _small_module(fused)
    if fused:
        assert mod._fused_ok
    mod._guardian = guardian.Guardian(clock=_fake_clock(), skip_max=2,
                                      warmup=4)

    clean = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    _step(mod, clean)
    assert mod._guardian_action == "ok"
    before = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    poisoned = clean.copy()
    poisoned[0, 0] = np.nan  # NaN propagates into every gradient
    _step(mod, poisoned)
    assert mod._guardian_action == "skip"
    after = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in before:
        assert np.array_equal(before[k], after[k]), \
            "%s changed across a skipped batch" % k

    # training continues: the next clean step applies
    _step(mod, clean)
    assert mod._guardian_action == "ok"
    final = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    assert any(not np.array_equal(before[k], final[k]) for k in before)


def test_injected_nan_fault_detected_on_eager_path():
    """The new ``nan`` corruption kind on ``guardian.grad``: the grads are
    rewritten between backward and update, the guard answers skip."""
    guardian.enable()
    faults.install(faults.FaultPlan("guardian.grad:nan@#1", seed=0))
    mod = _small_module(fused=True)  # corruption hook forces eager anyway
    assert not mod._fused_ok, \
        "scheduled guardian.grad corruption must fall back to eager"
    mod._guardian = guardian.Guardian(clock=_fake_clock(), skip_max=2,
                                      warmup=4)
    before = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    _step(mod, np.random.RandomState(1).randn(4, 6).astype(np.float32))
    assert mod._guardian_action == "skip"
    after = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in before:
        assert np.array_equal(before[k], after[k])
    assert guardian.stats()["anomalies"] == 1


# ---------------------------------------------------------------------------
# kvstore server: non-finite pushes are NACKed, never applied
# ---------------------------------------------------------------------------
def _server_pair():
    srv = kvs.KVStoreServer(num_workers=1)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, kvs.ServerClient(*srv.addr)


def test_nonfinite_dense_push_nacked_and_not_applied():
    srv, cli = _server_pair()
    try:
        cli.init(0, np.zeros(4, dtype=np.float32))
        cli.push(0, np.ones(4, dtype=np.float32), rank=0)
        want = cli.pull(0).tobytes()
        bad = np.ones(4, dtype=np.float32)
        bad[2] = np.nan
        with pytest.raises(kvs.NonFiniteGradientError):
            cli.push(0, bad, rank=3)
        assert cli.pull(0).tobytes() == want
        assert srv.rejected_pushes == 1
        assert srv.rejects_by_rank == {3: 1}
    finally:
        cli.close()
        srv.stop()


def test_nonfinite_sparse_push_nacked():
    srv, cli = _server_pair()
    try:
        cli.init_table("emb", {"num_rows": 8, "row_shape": (2,),
                               "init": ("zeros",), "dtype": "float32",
                               "num_servers": 1, "server_index": 0})
        with pytest.raises(kvs.NonFiniteGradientError):
            cli.push_rows("emb", np.array([1], dtype=np.int64),
                          np.full((1, 2), np.inf, dtype=np.float32), rank=5)
        rows = cli.pull_rows("emb", np.array([1], dtype=np.int64))
        assert not rows.any(), "NACKed sparse push reached the table"
        assert srv.rejects_by_rank == {5: 1}
    finally:
        cli.close()
        srv.stop()


def test_nack_is_exactly_once_under_retry():
    """A replayed envelope (same cid, seq) answers from the dedup window:
    the recorded NACK comes back, the rejection is not double-counted."""
    srv, cli = _server_pair()
    try:
        bad = np.full(4, np.nan, dtype=np.float32)
        r1 = srv._serve_one("cidX", 7, ("push", 0, bad, 9))
        r2 = srv._serve_one("cidX", 7, ("push", 0, bad, 9))
        assert r1[0] == "nack" and r2 == r1
        assert srv.rejects_by_rank == {9: 1}
    finally:
        cli.close()
        srv.stop()


def test_nack_gate_can_be_disabled(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_REJECT_NONFINITE", "0")
    srv, cli = _server_pair()
    try:
        cli.init(0, np.zeros(2, dtype=np.float32))
        cli.push(0, np.full(2, np.nan, dtype=np.float32), rank=0)  # no raise
        assert np.isnan(cli.pull(0)).all()
        assert srv.rejected_pushes == 0
    finally:
        cli.close()
        srv.stop()


def test_repeat_offender_evicted_at_nack_limit(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_NACK_LIMIT", "2")
    srv, cli = _server_pair()
    try:
        cli.init(0, np.zeros(2, dtype=np.float32))
        with srv._lock:
            srv._members.update({3, 4})
        bad = np.full(2, np.inf, dtype=np.float32)
        for _ in range(2):
            with pytest.raises(kvs.NonFiniteGradientError):
                cli.push(0, bad, rank=3)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with srv._lock:
                if 3 not in srv._members:
                    break
            time.sleep(0.01)
        with srv._lock:
            assert 3 not in srv._members, "poisoned rank not evicted"
            assert 4 in srv._members
    finally:
        cli.close()
        srv.stop()


# ---------------------------------------------------------------------------
# overhead guard: guardian off must stay near-free
# ---------------------------------------------------------------------------
def test_disabled_overhead_under_two_percent():
    """Off, each hook site costs one module-global bool read.  Budget:
    ~8 hook reads per step must stay under 2% of even a tiny CPU step."""
    assert not guardian.enabled()
    mod = _small_module(fused=False)
    assert mod._guardian is None

    n = 200_000
    per_gate_s = timeit.timeit(guardian.enabled, number=n) / n

    x = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    _step(mod, x)  # warm the compile caches
    t0 = time.perf_counter()
    for _ in range(20):
        _step(mod, x)
    step_s = (time.perf_counter() - t0) / 20

    hooks_per_step = 8  # fit snapshot gate + update guard + eager observe
    assert per_gate_s * hooks_per_step < 0.02 * step_s, \
        "guardian-off gate cost %.3fus x %d vs step %.1fus" % (
            per_gate_s * 1e6, hooks_per_step, step_s * 1e6)

"""Metrics, initializers, RNG, attribute scopes
(reference: test_metric via usage, test_init.py, test_random.py, test_attr.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


# -- metrics ---------------------------------------------------------------

def test_accuracy():
    m = mx.metric.Accuracy()
    preds = nd.array(np.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]],
                              np.float32))
    labels = nd.array(np.array([0.0, 1.0, 1.0], np.float32))
    m.update([labels], [preds])
    name, val = m.get()
    assert name == "accuracy"
    assert abs(val - 2.0 / 3.0) < 1e-6


def test_topk_accuracy():
    m = mx.metric.TopKAccuracy(top_k=2)
    preds = nd.array(np.array([[0.1, 0.2, 0.7], [0.8, 0.15, 0.05]],
                              np.float32))
    labels = nd.array(np.array([1.0, 2.0], np.float32))
    m.update([labels], [preds])
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_mse_mae_rmse():
    preds = nd.array(np.array([[1.0], [2.0]], np.float32))
    labels = nd.array(np.array([[0.0], [4.0]], np.float32))
    m = mx.metric.MSE()
    m.update([labels], [preds])
    assert abs(m.get()[1] - (1.0 + 4.0) / 2.0) < 1e-6
    m = mx.metric.MAE()
    m.update([labels], [preds])
    assert abs(m.get()[1] - 1.5) < 1e-6
    m = mx.metric.RMSE()
    m.update([labels], [preds])
    assert abs(m.get()[1] - np.sqrt(2.5)) < 1e-5


def test_cross_entropy_and_perplexity():
    preds = nd.array(np.array([[0.5, 0.5], [0.1, 0.9]], np.float32))
    labels = nd.array(np.array([0.0, 1.0], np.float32))
    m = mx.metric.CrossEntropy()
    m.update([labels], [preds])
    expected = -(np.log(0.5) + np.log(0.9)) / 2
    assert abs(m.get()[1] - expected) < 1e-5
    p = mx.metric.Perplexity(ignore_label=None)
    p.update([labels], [preds])
    assert abs(p.get()[1] - np.exp(expected)) < 1e-4


def test_f1():
    m = mx.metric.F1()
    preds = nd.array(np.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]],
                              np.float32))
    labels = nd.array(np.array([1.0, 0.0, 0.0], np.float32))
    m.update([labels], [preds])
    # tp=1 fp=1 fn=0 -> precision=0.5 recall=1 -> f1=2/3
    assert abs(m.get()[1] - 2.0 / 3.0) < 1e-6


def test_composite_metric():
    m = mx.metric.CompositeEvalMetric()
    m.add(mx.metric.Accuracy())
    m.add(mx.metric.CrossEntropy())
    preds = nd.array(np.array([[0.9, 0.1]], np.float32))
    labels = nd.array(np.array([0.0], np.float32))
    m.update([labels], [preds])
    names, vals = m.get()
    assert len(names) == 2


def test_custom_metric():
    m = mx.metric.CustomMetric(lambda l, p: np.abs(l - p).mean(), name="mad")
    m.update([nd.array([1.0])], [nd.array([3.0])])
    assert abs(m.get()[1] - 2.0) < 1e-6


def test_metric_create_by_name():
    assert mx.metric.create("acc").name == "accuracy"
    assert mx.metric.create("mse").name == "mse"
    comp = mx.metric.create(["acc", "mse"])
    assert isinstance(comp, mx.metric.CompositeEvalMetric)


# -- initializers ----------------------------------------------------------

def _init_array(init, name="weight", shape=(50, 40)):
    arr = nd.zeros(shape)
    desc = mx.init.InitDesc(name)
    init(desc, arr)
    return arr.asnumpy()


def test_uniform_normal_constant():
    a = _init_array(mx.init.Uniform(0.5))
    assert a.min() >= -0.5 and a.max() <= 0.5 and np.abs(a).sum() > 0
    a = _init_array(mx.init.Normal(2.0))
    assert abs(a.std() - 2.0) < 0.3
    a = _init_array(mx.init.Constant(3.0) if hasattr(mx.init, "Constant")
                    else mx.init.One())
    assert np.all(a != 0)


def test_xavier_magnitude():
    shape = (100, 80)
    a = _init_array(mx.init.Xavier(factor_type="avg", magnitude=3.0),
                    shape=shape)
    scale = np.sqrt(3.0 / ((shape[0] + shape[1]) / 2.0))
    assert a.min() >= -scale - 1e-5 and a.max() <= scale + 1e-5
    assert a.std() > scale / 4


def test_bias_initialized_zero():
    arr = nd.ones((10,))
    mx.init.Xavier()(mx.init.InitDesc("fc1_bias"), arr)
    assert_almost_equal(arr, np.zeros(10))


def test_orthogonal():
    a = _init_array(mx.init.Orthogonal(), shape=(20, 20))
    # columns orthogonal: A @ A.T ~ scale^2 * I
    prod = a @ a.T
    off = prod - np.diag(np.diag(prod))
    assert np.abs(off).max() < 1e-3


def test_init_desc_attrs_lr_mult_passthrough():
    # gamma inits to one, beta to zero
    arr = nd.zeros((4,))
    mx.init.Xavier()(mx.init.InitDesc("bn_gamma"), arr)
    assert_almost_equal(arr, np.ones(4))


# -- RNG -------------------------------------------------------------------

def test_seed_reproducibility():
    mx.random.seed(7)
    a = nd.random_uniform(shape=(5,)) if hasattr(nd, "random_uniform") else \
        nd.uniform(shape=(5,))
    mx.random.seed(7)
    b = nd.random_uniform(shape=(5,)) if hasattr(nd, "random_uniform") else \
        nd.uniform(shape=(5,))
    assert_almost_equal(a, b)


def test_different_calls_different_draws():
    mx.random.seed(7)
    a = nd.uniform(shape=(100,))
    b = nd.uniform(shape=(100,))
    assert np.abs(a.asnumpy() - b.asnumpy()).sum() > 1e-3


# -- attribute / name scopes ----------------------------------------------

def test_attr_scope():
    with mx.AttrScope(lr_mult="2.0"):
        v = sym.Variable("w")
    assert v.attr("lr_mult") == "2.0"


def test_attr_scope_nesting():
    with mx.AttrScope(group="a"):
        with mx.AttrScope(mult="3"):
            v = sym.Variable("x")
    assert v.attr("group") == "a"
    assert v.attr("mult") == "3"


def test_name_manager_auto_naming():
    data = sym.Variable("data")
    s1 = sym.FullyConnected(data=data, num_hidden=2)
    s2 = sym.FullyConnected(data=data, num_hidden=2)
    assert s1.name != s2.name


def test_prefix_name_manager():
    with mx.name.Prefix("mynet_"):
        data = sym.Variable("data")
        s = sym.FullyConnected(data=data, num_hidden=2)
    assert s.name.startswith("mynet_")

"""Predictor/serving tests: checkpoint round-trip through the static bound
forward (parity: /root/reference/src/c_api/c_predict_api.cc:41-280) and the
jax.export AOT artifact (amalgamation-equivalent deployment)."""
import numpy as np

import mxnet_tpu as mx


def _train_and_checkpoint(tmp_path):
    np.random.seed(1)
    X = np.random.randn(60, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    # module-path reference outputs on a fixed batch
    batch = X[:10]
    it2 = mx.io.NDArrayIter(X[:10], y[:10], batch_size=10)
    ref = mod.predict(it2).asnumpy() if hasattr(mod, "predict") else None
    return prefix, batch, ref, mod


def test_predictor_checkpoint_roundtrip(tmp_path):
    prefix, batch, ref, mod = _train_and_checkpoint(tmp_path)
    pred = mx.Predictor("%s-symbol.json" % prefix,
                        "%s-0003.params" % prefix,
                        {"data": (10, 6), "softmax_label": (10,)})
    outs = pred.forward(data=batch)
    probs = outs[0].asnumpy()
    assert probs.shape == (10, 2)
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(10), rtol=1e-5)
    if ref is not None:
        np.testing.assert_allclose(probs, ref, rtol=1e-4, atol=1e-5)
    # set_input + forward + get_output (the C API call sequence)
    pred.set_input("data", batch)
    pred._exec.forward(is_train=False)
    np.testing.assert_allclose(pred.get_output(0).asnumpy(), probs,
                               rtol=1e-6)


def test_predictor_reshape(tmp_path):
    prefix, batch, _, _ = _train_and_checkpoint(tmp_path)
    pred = mx.Predictor("%s-symbol.json" % prefix, "%s-0003.params" % prefix,
                        {"data": (10, 6), "softmax_label": (10,)})
    pred4 = pred.reshape({"data": (4, 6), "softmax_label": (4,)})
    outs = pred4.forward(data=batch[:4])
    assert outs[0].shape == (4, 2)
    big = pred.forward(data=batch)[0].asnumpy()
    np.testing.assert_allclose(outs[0].asnumpy(), big[:4], rtol=1e-4,
                               atol=1e-6)


def test_exported_artifact_roundtrip(tmp_path):
    prefix, batch, _, _ = _train_and_checkpoint(tmp_path)
    pred = mx.Predictor("%s-symbol.json" % prefix, "%s-0003.params" % prefix,
                        {"data": (10, 6), "softmax_label": (10,)})
    want = pred.forward(data=batch)[0].asnumpy()
    path = str(tmp_path / "model.mxtpu")
    pred.export(path)
    served = mx.load_exported(path)
    assert served.input_names[0] == "data"
    got = np.asarray(served.forward(
        data=batch, softmax_label=np.zeros(10, np.float32))[0])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_predictor_from_checkpoint_zero_fills_labels(tmp_path):
    """from_checkpoint consumes save_checkpoint's file pair directly; the
    training symbol's loss label binds as zeros at inference (reference
    MXPredCreate allocates missing args zero-filled)."""
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, optimizer="sgd")
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 2)

    pred = mx.Predictor.from_checkpoint(prefix, 2, {"data": (8, 6)})
    out = pred.forward(data=X[:8])[0].asnumpy()
    it.reset()
    mod.forward(next(iter(it)), is_train=False)
    np.testing.assert_allclose(out, mod.get_outputs()[0].asnumpy(),
                               rtol=1e-5)


def test_predictor_reshape_after_from_checkpoint(tmp_path):
    """reshape() on a checkpoint whose symbol carries a loss label: the
    zero-filled label must be re-synthesized at the new batch size."""
    rng = np.random.RandomState(1)
    X = rng.randn(16, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    mod = mx.mod.Module(net)
    mod.fit(mx.io.NDArrayIter(X, y, batch_size=8), num_epoch=1,
            optimizer="sgd")
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    pred = mx.Predictor.from_checkpoint(prefix, 1, {"data": (8, 5)})
    small = pred.reshape({"data": (2, 5)})
    a = small.forward(data=X[:2])[0].asnumpy()
    b = pred.forward(data=X[:8])[0].asnumpy()[:2]
    np.testing.assert_allclose(a, b, rtol=1e-5)

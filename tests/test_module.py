"""Module/fit training API tests incl. convergence gate
(reference: tests/python/unittest/test_module.py + tests/python/train/test_mlp.py;
convergence thresholds follow tests/nightly/test_all.sh:54-60)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def _toy_classification(n=400, d=10, k=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, k).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    return X, y


def _mlp(k=3):
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=32, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=k, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_module_bind_init_forward():
    net = _mlp()
    mod = mx.mod.Module(net, label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.init.Xavier())
    batch = mx.io.DataBatch(data=[nd.zeros((8, 10))],
                            label=[nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 3)
    # uniform softmax on zero input with zero bias
    assert_almost_equal(out.asnumpy().sum(axis=1), np.ones(8), rtol=1e-4,
                        atol=1e-4)


def test_module_fit_converges():
    X, y = _toy_classification()
    train = mx.io.NDArrayIter(X, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.fit(train, num_epoch=15, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="acc",
            initializer=mx.init.Xavier())
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=40),
                      mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc >= 0.95, "MLP failed to fit toy data: acc=%f" % acc


def test_module_fit_with_eval_data_and_callbacks():
    X, y = _toy_classification()
    train = mx.io.NDArrayIter(X[:300], y[:300], batch_size=30, shuffle=True)
    val = mx.io.NDArrayIter(X[300:], y[300:], batch_size=30)
    epochs_seen = []
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            epoch_end_callback=lambda e, s, a, x: epochs_seen.append(e),
            batch_end_callback=mx.callback.Speedometer(30, frequent=5))
    assert epochs_seen == [0, 1, 2]


def test_module_predict():
    X, y = _toy_classification(n=64)
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (64, 3)


def test_module_save_load_checkpoint():
    X, y = _toy_classification(n=80)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.fit(train, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "model")
        mod.save_checkpoint(prefix, 2)
        assert os.path.exists(prefix + "-symbol.json")
        assert os.path.exists(prefix + "-0002.params")
        mod2 = mx.mod.Module.load(prefix, 2, label_names=("softmax_label",))
        mod2.bind(data_shapes=train.provide_data,
                  label_shapes=train.provide_label)
        arg1, _ = mod.get_params()
        arg2, _ = mod2.get_params()
        for k in arg1:
            assert_almost_equal(arg1[k], arg2[k])


def test_module_get_set_params():
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    args, aux = mod.get_params()
    args["fc1_weight"] = nd.ones(args["fc1_weight"].shape)
    mod.set_params(args, aux)
    args2, _ = mod.get_params()
    assert_almost_equal(args2["fc1_weight"],
                        np.ones(args["fc1_weight"].shape, np.float32))


def test_module_grad_array_access():
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))], for_training=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.array(np.random.randn(4, 10)
                                           .astype(np.float32))],
                            label=[nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    # gradient arrays on the exec group must be populated after backward
    assert mod._exec_group is not None
    assert any(g is not None for g in mod._exec_group.grad_arrays)


def test_lenet_mnist_style_convergence():
    """LeNet on a synthetic MNIST-like task (reference CI gate:
    tests/nightly/test_all.sh:54-60 requires lenet val-acc >= 0.99; here the
    task is synthetic since the image has no dataset egress)."""
    rng = np.random.RandomState(42)
    n, k = 256, 4
    # well-separated blobs rendered into 1x16x16 images
    X = np.zeros((n, 1, 16, 16), np.float32)
    y = rng.randint(0, k, n).astype(np.float32)
    for i in range(n):
        c = int(y[i])
        X[i, 0, 4 * (c // 2):4 * (c // 2) + 4,
          4 * (c % 2):4 * (c % 2) + 4] = 1.0
    X += rng.randn(*X.shape).astype(np.float32) * 0.1

    data = sym.Variable("data")
    net = sym.Convolution(data=data, num_filter=8, kernel=(3, 3), name="c1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.Pooling(data=net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = sym.Flatten(data=net)
    net = sym.FullyConnected(data=net, num_hidden=k, name="fc")
    net = sym.SoftmaxOutput(data=net, name="softmax")

    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(net, label_names=("softmax_label",))
    mod.fit(train, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Xavier())
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32),
                      mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc >= 0.99, "LeNet-style conv net under 0.99 gate: %f" % acc


def test_module_reshape_preserves_params():
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Uniform(0.5))
    before, _ = mod.get_params()
    mod.reshape(data_shapes=[("data", (6, 10))],
                label_shapes=[("softmax_label", (6,))])
    after, _ = mod.get_params()
    for k in before:
        assert_almost_equal(before[k], after[k],
                            names=("before[%s]" % k, "after[%s]" % k))
    batch = mx.io.DataBatch(data=[nd.zeros((6, 10))],
                            label=[nd.zeros((6,))])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (6, 3)


def test_feedforward_load_then_score():
    X, y = _toy_classification(n=64)
    ff = mx.model.FeedForward(_mlp(), num_epoch=2, optimizer="sgd",
                              learning_rate=0.3)
    ff.fit(mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True))
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "ffmodel")
        ff.save(prefix)
        loaded = mx.model.FeedForward.load(prefix, 2)
        acc = loaded.score(mx.io.NDArrayIter(X, y, batch_size=16))
    assert 0.0 <= acc <= 1.0


def test_feedforward_api():
    X, y = _toy_classification(n=80)
    ff = mx.model.FeedForward(_mlp(), num_epoch=3, optimizer="sgd",
                              learning_rate=0.3)
    ff.fit(mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True))
    preds = ff.predict(mx.io.NDArrayIter(X, y, batch_size=16))
    assert preds.shape == (80, 3)


def test_bucketing_module_lm_convergence():
    """BucketingModule end-to-end (the lstm_bucketing example path,
    BASELINE config #3): multi-bucket LSTM LM on a learnable synthetic
    Markov corpus; perplexity must fall vs the untrained model."""
    rng = np.random.RandomState(0)
    vocab = 16
    trans = np.zeros((vocab, vocab))
    for i in range(vocab):
        nxt = rng.choice(vocab, size=2, replace=False)
        trans[i, nxt] = rng.dirichlet(np.ones(2))
    sents = []
    for _ in range(160):
        length = rng.randint(4, 13)
        s = [int(rng.randint(vocab))]
        for _ in range(length - 1):
            s.append(int(rng.choice(vocab, p=trans[s[-1]])))
        sents.append(s)
    buckets = [6, 12]
    train = mx.rnn.BucketSentenceIter(sents, 8, buckets=buckets,
                                      invalid_label=0)

    cell = mx.rnn.LSTMCell(num_hidden=32, prefix="lstm_")

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab,
                                 output_dim=16, name="embed")
        cell.reset()
        outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, 32))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        return (mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax"),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.default_bucket_key)

    def perplexity():
        m = mx.metric.Perplexity(ignore_label=0)
        train.reset()
        mod.score(train, m)
        return m.get()[1]

    # untrained baseline: bind + init only (a second fit would keep the
    # first fit's optimizer — init_optimizer skips when already set up,
    # matching the reference)
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(initializer=mx.init.Xavier(factor_type="in",
                                               magnitude=2.34))
    before = perplexity()
    train.reset()
    mod.fit(train, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 0.01})
    after = perplexity()
    assert after < before * 0.7, (before, after)
    # both buckets must have produced shared-parameter executors
    assert len(mod._buckets) >= 2

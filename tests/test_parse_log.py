"""tools/parse_log.py: parse a REAL training log produced by Module.fit +
Speedometer and gate on accuracy (reference CI pattern,
tests/nightly/test_all.sh:43-60)."""
import json
import logging
import os
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _train_with_log(tmp_path):
    logfile = str(tmp_path / "train.log")
    logger = logging.getLogger("parse_log_test")
    logger.setLevel(logging.INFO)
    handler = logging.FileHandler(logfile)
    handler.setFormatter(logging.Formatter("%(asctime)-15s %(message)s"))
    logger.addHandler(handler)
    # Speedometer logs through the root logger
    root_handler = logging.FileHandler(logfile)
    logging.getLogger().addHandler(root_handler)
    try:
        np.random.seed(0)
        X = np.random.randn(120, 10).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=12)
        val = mx.io.NDArrayIter(X, y, batch_size=12)
        net = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
            name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu(), logger=logger)
        mod.fit(it, eval_data=val, num_epoch=3, optimizer="sgd",
                optimizer_params={"learning_rate": 0.3},
                batch_end_callback=mx.callback.Speedometer(12, 5))
    finally:
        logger.removeHandler(handler)
        logging.getLogger().removeHandler(root_handler)
        handler.close()
        root_handler.close()
    return logfile


def test_parse_log_end_to_end(tmp_path):
    logfile = _train_with_log(tmp_path)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         logfile, "--format", "json"],
        capture_output=True, text=True, check=True)
    epochs = json.loads(out.stdout)
    assert set(epochs) == {"0", "1", "2"}
    for rec in epochs.values():
        assert "train-accuracy" in rec and "time_cost" in rec
        assert "validation-accuracy" in rec
        assert rec.get("speed", 1.0) > 0
    # accuracy improves and the CI gate passes
    gate = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         logfile, "--metric", "validation-accuracy", "--last",
         "--assert-min", "0.9"],
        capture_output=True, text=True)
    assert gate.returncode == 0, (gate.stdout, gate.stderr)
    assert float(gate.stdout.strip()) > 0.9
    # and fails when the bar is unreachable
    gate2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         logfile, "--metric", "validation-accuracy", "--last",
         "--assert-min", "1.01"],
        capture_output=True, text=True)
    assert gate2.returncode == 1

"""Fault-injection engine + durable-write/corruption-detection tests.

The deterministic half of the chaos story: FaultPlan decisions are pure
functions of (spec, seed, call index), atomic_write leaves only
old-complete or new-complete bytes behind, and every reader that
discovers persisted artifacts (checkpoints, RecordIO, kv snapshots)
rejects torn or corrupted files instead of loading garbage.
"""

import os
import struct
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu.faults import (FaultPlan, InjectedConnectionError,
                              InjectedIOError, parse_spec)
from mxnet_tpu import filesystem as fs
from mxnet_tpu.recordio import MXRecordIO, RecordIOCorruptError


# -- spec grammar -----------------------------------------------------------

def test_parse_spec_grammar():
    rules = parse_spec("kv.client.*:drop=0.3;ckpt.write:partial=1@0.5,"
                       "ioerr=0.1;*:delay=1@10ms")
    assert [(r.op, r.kind) for r in rules] == [
        ("kv.client.*", "drop"), ("ckpt.write", "partial"),
        ("ckpt.write", "ioerr"), ("*", "delay")]
    assert rules[0].rate == 0.3
    assert rules[1].param == 0.5
    assert rules[3].param == pytest.approx(0.01)  # 10ms -> seconds


def test_parse_spec_nth_trigger_and_errors():
    (rule,) = parse_spec("kv.client.recv:drop=1@#2")
    assert rule.nth == 2 and rule.param is None
    with pytest.raises(ValueError, match="bad fault rule"):
        parse_spec("no-colon-here")
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_spec("op:explode=1")


# -- decision engine --------------------------------------------------------

def _decision_trace(plan, ops):
    out = []
    for op in ops:
        try:
            plan.fire(op)
            out.append(None)
        except (InjectedConnectionError, InjectedIOError) as e:
            out.append(type(e).__name__)
    return out


def test_same_seed_same_decisions():
    ops = ["kv.client.send", "kv.client.recv"] * 50
    t1 = _decision_trace(FaultPlan("kv.client.*:drop=0.5", seed=11), ops)
    t2 = _decision_trace(FaultPlan("kv.client.*:drop=0.5", seed=11), ops)
    t3 = _decision_trace(FaultPlan("kv.client.*:drop=0.5", seed=12), ops)
    assert t1 == t2
    assert t1 != t3  # astronomically unlikely to collide over 100 draws
    assert any(t1)


def test_rule_streams_are_independent():
    """Interleaving calls to OTHER ops must not shift a rule's decision
    sequence — each rule draws from its own seeded stream."""
    spec = "a.x:drop=0.5;b.*:drop=0.5"
    plain = _decision_trace(FaultPlan(spec, seed=3), ["a.x"] * 40)
    mixed_ops = []
    for _ in range(40):
        mixed_ops += ["a.x", "b.y", "b.y"]
    mixed = _decision_trace(FaultPlan(spec, seed=3), mixed_ops)
    assert [d for op, d in zip(mixed_ops, mixed) if op == "a.x"] == plain


def test_nth_trigger_fires_exactly_once():
    plan = FaultPlan("kv.client.recv:drop=1@#3", seed=0)
    trace = _decision_trace(plan, ["kv.client.recv"] * 6)
    assert trace == [None, None, "InjectedConnectionError",
                     None, None, None]
    assert plan.events == [("kv.client.recv", "drop", 3)]


def test_inject_scoping_restores_previous_plan():
    assert faults.active() is None
    with faults.inject("x:drop=1"):
        assert faults.active() is not None
        with pytest.raises(InjectedConnectionError):
            faults.fire("x")
        with faults.inject("y:ioerr=1") as inner:
            assert faults.active() is inner
            faults.fire("x")  # old plan no longer consulted
        with pytest.raises(InjectedConnectionError):
            faults.fire("x")
    assert faults.active() is None
    faults.fire("x")  # inactive: must be a no-op


def test_install_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_FAULTS_SPEC", "env.op:ioerr=1")
    monkeypatch.setenv("MXNET_FAULTS_SEED", "5")
    try:
        plan = faults.install_from_env()
        assert plan is not None and plan.seed == 5
        with pytest.raises(InjectedIOError):
            faults.fire("env.op")
    finally:
        faults.uninstall()


# -- atomic writes + CRC sidecars -------------------------------------------

def test_atomic_write_success_and_sidecar(tmp_path):
    p = str(tmp_path / "state.bin")
    fs.atomic_write(p, lambda f: f.write(b"hello world"), checksum=True)
    assert open(p, "rb").read() == b"hello world"
    assert fs.verify_crc_sidecar(p) is True
    # silent corruption after the fact is caught by the sidecar
    with open(p, "r+b") as f:
        f.write(b"J")
    assert fs.verify_crc_sidecar(p) is False
    assert fs.verify_crc_sidecar(str(tmp_path / "nosidecar")) is None


def test_atomic_write_torn_write_leaves_old_file_intact(tmp_path):
    p = str(tmp_path / "ckpt.params")
    fs.atomic_write(p, lambda f: f.write(b"GOOD" * 64), op="ckpt.write")
    with faults.inject("ckpt.write:partial=1@0.5"):
        with pytest.raises(InjectedIOError, match="torn write"):
            fs.atomic_write(p, lambda f: f.write(b"NEWDATA" * 64),
                            op="ckpt.write")
    # the visible file is still the OLD complete version
    assert open(p, "rb").read() == b"GOOD" * 64
    # ...and the torn temp is around, truncated, as after a real crash
    torn = "%s.tmp.%d" % (p, os.getpid())
    assert os.path.exists(torn)
    assert len(open(torn, "rb").read()) == len(b"NEWDATA" * 64) // 2


def test_nd_save_is_atomic_under_injected_crash(tmp_path):
    p = str(tmp_path / "w.params")
    good = {"w": mx.nd.array(np.arange(8, dtype=np.float32))}
    mx.nd.save(p, good)
    with faults.inject("params.write:ioerr=1@#1"):
        with pytest.raises(InjectedIOError):
            mx.nd.save(p, {"w": mx.nd.array(np.zeros(8, np.float32))})
    loaded = mx.nd.load(p)
    np.testing.assert_array_equal(loaded["w"].asnumpy(),
                                  np.arange(8, dtype=np.float32))


# -- checkpoint discovery skips corrupt files -------------------------------

def test_find_latest_checkpoint_skips_corrupt(tmp_path):
    import jax.numpy as jnp

    prefix = str(tmp_path / "model")
    arg = {"w": mx.nd.array(jnp.ones((2, 2)))}
    mx.model.save_checkpoint(prefix, 1, None, arg, {})
    assert fs.verify_crc_sidecar("%s-0001.params" % prefix) is True
    mx.model.save_checkpoint(prefix, 2, None, arg, {})
    # epoch 2 gets torn after the save (bit rot / partial copy): the CRC
    # sidecar no longer matches
    with open("%s-0002.params" % prefix, "r+b") as f:
        f.truncate(10)
    # epoch 3 is a sidecar-less impostor with garbage bytes: rejected by
    # the container-magic sniff
    with open("%s-0003.params" % prefix, "wb") as f:
        f.write(b"not a params file")
    assert mx.model.find_latest_checkpoint(prefix) == 1


def test_save_checkpoint_atomic_under_torn_write(tmp_path):
    import jax.numpy as jnp

    prefix = str(tmp_path / "net")
    mx.model.save_checkpoint(prefix, 1, None,
                             {"w": mx.nd.array(jnp.full((3,), 7.0))}, {})
    with faults.inject("ckpt.write:partial=1@0.4"):
        with pytest.raises(InjectedIOError):
            mx.model.save_checkpoint(
                prefix, 1, None,
                {"w": mx.nd.array(jnp.zeros((3,)))}, {})
    # resume still finds the intact epoch and loads the OLD weights
    assert mx.model.find_latest_checkpoint(prefix) == 1
    loaded = mx.nd.load("%s-0001.params" % prefix)
    np.testing.assert_array_equal(loaded["arg:w"].asnumpy(),
                                  np.full((3,), 7.0))


def test_sharded_checkpoint_incomplete_dir_is_rejected(tmp_path):
    from mxnet_tpu import checkpoint as ckpt

    path = tmp_path / "m-0001.orbax"
    path.mkdir()  # a crash-torn orbax dir: exists but never committed
    (path / "somefile").write_bytes(b"partial")
    with pytest.raises(mx.MXNetError, match="incomplete"):
        ckpt.load_sharded_checkpoint(str(tmp_path / "m"), 1)


# -- RecordIO corruption ----------------------------------------------------

def _write_records(path, payloads):
    w = MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()


def test_recordio_truncated_trailing_record_raises_with_offset(tmp_path):
    p = str(tmp_path / "data.rec")
    _write_records(p, [b"a" * 32, b"b" * 32])
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - 30)  # tear the second record's payload
    r = MXRecordIO(p, "r")
    assert r.read() == b"a" * 32
    with pytest.raises(RecordIOCorruptError) as ei:
        r.read()
    assert ei.value.offset == 40  # second record starts after 8+32 bytes
    assert "byte offset 40" in str(ei.value)
    r.close()
    # a trailing partial HEADER (writer died inside the 8-byte head) is
    # also corruption, not silent end-of-stream
    with open(p, "r+b") as f:
        f.truncate(43)
    r = MXRecordIO(p, "r")
    assert r.read() == b"a" * 32
    with pytest.raises(RecordIOCorruptError, match="trailing record header"):
        r.read()
    r.close()


def test_recordio_bad_magic_raises_with_offset(tmp_path):
    p = str(tmp_path / "data.rec")
    _write_records(p, [b"x" * 8])
    with open(p, "r+b") as f:
        f.write(struct.pack("<I", 0xdeadbeef))
    r = MXRecordIO(p, "r")
    with pytest.raises(RecordIOCorruptError, match="invalid RecordIO magic"):
        r.read()
    r.close()


def test_recordio_clean_eof_still_returns_none(tmp_path):
    p = str(tmp_path / "data.rec")
    _write_records(p, [b"one"])
    r = MXRecordIO(p, "r")
    assert r.read() == b"one"
    assert r.read() is None
    r.close()

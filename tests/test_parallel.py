"""Sequence/pipeline parallelism over the 8-virtual-device CPU mesh — exact
against single-device oracles (the reference has no such capability; these
are the new first-class components of SURVEY.md §7 step 8)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.test_utils import assert_almost_equal


def _qkv(b=2, s=32, h=4, d=8, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(b, s, h, d).astype(dtype)
    k = rng.randn(b, s, h, d).astype(dtype)
    v = rng.randn(b, s, h, d).astype(dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_local(causal):
    import jax.numpy as jnp

    q, k, v = _qkv()
    mesh = parallel.make_mesh({"seq": 8})
    ref = parallel.local_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=causal)
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, causal=causal)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_local(causal):
    """Ring attention with the Pallas flash kernel as the per-block
    compute — fwd AND custom ring-level vjp vs the dense oracle."""
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(b=2, s=128, h=2, d=16)
    mesh = parallel.make_mesh({"seq": 4}, devices=jax.devices()[:4])
    qj, kj, vj = (jnp.asarray(t) for t in (q, k, v))
    ref = parallel.local_attention(qj, kj, vj, causal=causal)
    out = parallel.ring_flash_attention(qj, kj, vj, mesh, causal=causal,
                                        block_q=32, block_k=32)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g = jax.grad(loss(lambda q, k, v: parallel.ring_flash_attention(
        q, k, v, mesh, causal=causal, block_q=32, block_k=32)),
        argnums=(0, 1, 2))(qj, kj, vj)
    gr = jax.grad(loss(lambda q, k, v: parallel.local_attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(qj, kj, vj)
    for a, b in zip(g, gr):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_local(causal):
    import jax.numpy as jnp

    q, k, v = _qkv(h=8)
    mesh = parallel.make_mesh({"seq": 8})
    ref = parallel.local_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=causal)
    out = parallel.ulysses_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), mesh, causal=causal)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_ring_attention_2d_mesh_batch_sharded():
    """dp x sp: batch on 'data', sequence on 'seq'."""
    import jax.numpy as jnp

    q, k, v = _qkv(b=4, s=16)
    mesh = parallel.make_mesh({"data": 2, "seq": 4})
    ref = parallel.local_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=True)
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, axis="seq",
                                  batch_axis="data", causal=True)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_ring_attention_grads_match():
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(s=16)
    mesh = parallel.make_mesh({"seq": 8})

    def loss_ring(q, k, v):
        return parallel.ring_attention(q, k, v, mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return parallel.local_attention(q, k, v, causal=True).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g_ring, g_ref):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-4)


def test_pipeline_spmd_matches_sequential():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    n_stages, d, batch = 4, 6, 8
    ws = rng.randn(n_stages, d, d).astype(np.float32) * 0.3
    x = rng.randn(batch, d).astype(np.float32)

    def stage_fn(w, a):
        return jnp.tanh(a @ w)

    import jax

    mesh = parallel.make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    out = parallel.pipeline_spmd(stage_fn, jnp.asarray(ws), jnp.asarray(x),
                                 mesh, axis="pipe", n_microbatches=4)
    ref = x
    for i in range(n_stages):
        ref = np.tanh(ref @ ws[i])
    assert_almost_equal(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_mesh_config_infer():
    mesh = parallel.make_mesh({"data": -1, "model": 2})
    assert mesh.shape["model"] == 2
    assert mesh.shape["data"] * 2 == len(mesh.devices.ravel())


def test_current_mesh_scope():
    mesh = parallel.data_parallel_mesh()
    assert parallel.current_mesh() is None
    with parallel.set_current_mesh(mesh):
        assert parallel.current_mesh() is mesh
    assert parallel.current_mesh() is None


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_expert_parallel_matches_reference(top_k):
    """Expert-parallel MoE FFN (experts sharded over the mesh, psum
    combine) vs the dense single-device oracle — fwd and gradients."""
    import jax
    import jax.numpy as jnp

    mesh = parallel.make_mesh({"expert": 4}, devices=jax.devices()[:4])
    b, s, d, h, E = 2, 6, 8, 16, 8
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(b, s, d).astype(np.float32))
    gw = jnp.asarray(rng.randn(d, E).astype(np.float32)) * 0.5
    w1 = jnp.asarray(rng.randn(E, d, h).astype(np.float32)) * 0.3
    w2 = jnp.asarray(rng.randn(E, h, d).astype(np.float32)) * 0.3
    out = parallel.moe_ffn(x, gw, w1, w2, mesh, top_k=top_k)
    ref = parallel.moe_ffn_reference(x, gw, w1, w2, top_k=top_k)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-6)
    g = jax.grad(lambda w: jnp.sum(
        parallel.moe_ffn(x, gw, w, w2, mesh, top_k=top_k) ** 2))(w1)
    gr = jax.grad(lambda w: jnp.sum(
        parallel.moe_ffn_reference(x, gw, w, w2, top_k=top_k) ** 2))(w1)
    assert_almost_equal(np.asarray(g), np.asarray(gr),
                        rtol=1e-4, atol=1e-5)


def test_moe_validates_expert_divisibility():
    import jax
    import jax.numpy as jnp

    mesh = parallel.make_mesh({"expert": 4}, devices=jax.devices()[:4])

    x = jnp.zeros((1, 2, 4))
    with pytest.raises(ValueError, match="divisible"):
        parallel.moe_ffn(x, jnp.zeros((4, 6)), jnp.zeros((6, 4, 8)),
                         jnp.zeros((6, 8, 4)), mesh)
    with pytest.raises(ValueError, match="gate has"):
        parallel.moe_ffn(x, jnp.zeros((4, 8)), jnp.zeros((4, 4, 8)),
                         jnp.zeros((4, 8, 4)), mesh)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_vma_typing(monkeypatch, causal):
    """Trace the ring fwd+bwd under shard_map(check_vma=True) — the TPU
    varying-axis checker. Pallas interpret mode itself trips the checker
    (unrelated dynamic_slice issue), so the kernels are swapped for dense
    stand-ins with identical signatures/outputs; what this validates is
    the ring code's own typing: every lax.switch branch (including the
    causal skip branches) and every scan carry must agree."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu.ops import attention as att
    from mxnet_tpu.parallel import ring
    from mxnet_tpu.parallel._compat import shard_map

    def dense_fwd(q, k, v, causal, scale, bq, bk, interpret):
        b, s, h, d = q.shape
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        if causal:
            m = jnp.arange(s)[:, None] >= jnp.arange(k.shape[1])[None, :]
            sc = jnp.where(m[None, None], sc, -1e30)
        mx_ = sc.max(-1, keepdims=True)
        p = jnp.exp(sc - mx_)
        l = p.sum(-1, keepdims=True)
        o = jnp.einsum("bhqk,bkhd->bqhd", p / l, v).astype(q.dtype)
        lse = (mx_[..., 0] + jnp.log(l[..., 0])).reshape(b * h, s)
        return o, lse

    def dense_bwd(q, k, v, o, lse, do, causal, scale, bq, bk, interpret,
                  pre=None):
        _, vjp = jax.vjp(
            lambda q, k, v: dense_fwd(q, k, v, causal, scale, bq, bk,
                                      interpret)[0], q, k, v)
        return vjp(do)

    monkeypatch.setattr(att, "_flash_forward", dense_fwd)
    monkeypatch.setattr(att, "_flash_backward", dense_bwd)

    mesh = parallel.make_mesh({"seq": 4},
                              devices=jax.devices()[:4])
    q, k, v = (jnp.asarray(t) for t in _qkv(b=1, s=64, h=2, d=8))
    scale = 1.0 / np.sqrt(8)
    kw = dict(axis="seq", vary_axes=("seq",), n_shards=4, causal=causal,
              scale=scale, block_q=16, block_k=16, interpret=True)
    spec = P(None, "seq", None, None)

    def fwd_then_bwd(q, k, v):
        o, lse = ring._ring_flash_fwd(q, k, v, **kw)
        dq, dk, dv = ring._ring_flash_bwd(q, k, v, o, lse,
                                          jnp.ones_like(o), **kw)
        return o, dq, dk, dv

    fn = shard_map(fwd_then_bwd, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=(spec, spec, spec, spec), check_vma=True)
    o, dq, dk, dv = fn(q, k, v)  # raises TypeError on any vma mismatch
    ref = parallel.local_attention(q, k, v, causal=causal)
    assert_almost_equal(np.asarray(o), np.asarray(ref),
                        rtol=1e-4, atol=1e-5)

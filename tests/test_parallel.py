"""Sequence/pipeline parallelism over the 8-virtual-device CPU mesh — exact
against single-device oracles (the reference has no such capability; these
are the new first-class components of SURVEY.md §7 step 8)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.test_utils import assert_almost_equal


def _qkv(b=2, s=32, h=4, d=8, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    q = rng.randn(b, s, h, d).astype(dtype)
    k = rng.randn(b, s, h, d).astype(dtype)
    v = rng.randn(b, s, h, d).astype(dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_local(causal):
    import jax.numpy as jnp

    q, k, v = _qkv()
    mesh = parallel.make_mesh({"seq": 8})
    ref = parallel.local_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=causal)
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, causal=causal)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_local(causal):
    """Ring attention with the Pallas flash kernel as the per-block
    compute — fwd AND custom ring-level vjp vs the dense oracle."""
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(b=2, s=128, h=2, d=16)
    mesh = parallel.make_mesh({"seq": 4}, devices=jax.devices()[:4])
    qj, kj, vj = (jnp.asarray(t) for t in (q, k, v))
    ref = parallel.local_attention(qj, kj, vj, causal=causal)
    out = parallel.ring_flash_attention(qj, kj, vj, mesh, causal=causal,
                                        block_q=32, block_k=32)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g = jax.grad(loss(lambda q, k, v: parallel.ring_flash_attention(
        q, k, v, mesh, causal=causal, block_q=32, block_k=32)),
        argnums=(0, 1, 2))(qj, kj, vj)
    gr = jax.grad(loss(lambda q, k, v: parallel.local_attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(qj, kj, vj)
    for a, b in zip(g, gr):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_local(causal):
    import jax.numpy as jnp

    q, k, v = _qkv(h=8)
    mesh = parallel.make_mesh({"seq": 8})
    ref = parallel.local_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=causal)
    out = parallel.ulysses_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), mesh, causal=causal)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_ring_attention_2d_mesh_batch_sharded():
    """dp x sp: batch on 'data', sequence on 'seq'."""
    import jax.numpy as jnp

    q, k, v = _qkv(b=4, s=16)
    mesh = parallel.make_mesh({"data": 2, "seq": 4})
    ref = parallel.local_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=True)
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, axis="seq",
                                  batch_axis="data", causal=True)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_ring_attention_grads_match():
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(s=16)
    mesh = parallel.make_mesh({"seq": 8})

    def loss_ring(q, k, v):
        return parallel.ring_attention(q, k, v, mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return parallel.local_attention(q, k, v, causal=True).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g_ring, g_ref):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-4)


def test_pipeline_spmd_matches_sequential():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    n_stages, d, batch = 4, 6, 8
    ws = rng.randn(n_stages, d, d).astype(np.float32) * 0.3
    x = rng.randn(batch, d).astype(np.float32)

    def stage_fn(w, a):
        return jnp.tanh(a @ w)

    import jax

    mesh = parallel.make_mesh({"pipe": 4}, devices=jax.devices()[:4])
    out = parallel.pipeline_spmd(stage_fn, jnp.asarray(ws), jnp.asarray(x),
                                 mesh, axis="pipe", n_microbatches=4)
    ref = x
    for i in range(n_stages):
        ref = np.tanh(ref @ ws[i])
    assert_almost_equal(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_mesh_config_infer():
    mesh = parallel.make_mesh({"data": -1, "model": 2})
    assert mesh.shape["model"] == 2
    assert mesh.shape["data"] * 2 == len(mesh.devices.ravel())


def test_current_mesh_scope():
    mesh = parallel.data_parallel_mesh()
    assert parallel.current_mesh() is None
    with parallel.set_current_mesh(mesh):
        assert parallel.current_mesh() is mesh
    assert parallel.current_mesh() is None

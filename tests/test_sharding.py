"""GSPMD named-mesh partitioning (mxnet_tpu.sharding): mesh building,
regex rules -> PartitionSpec, placement helpers, and the sharded fused
train step on the 8-virtual-device CPU mesh — including 2-D
("data","model") tensor parallelism matching single-device training."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sharding
from mxnet_tpu.base import MXNetError


def P(*args):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*args)


# ----------------------------------------------------------------------
# mesh construction
# ----------------------------------------------------------------------
def test_build_mesh_infers_axis():
    mesh = sharding.build_mesh("data=-1,model=2")
    assert sharding.mesh_axes(mesh) == {"data": 4, "model": 2}
    assert mesh.axis_names == ("data", "model")


def test_build_mesh_forms():
    assert sharding.mesh_axes(sharding.build_mesh()) == {"data": 8}
    assert sharding.mesh_axes(sharding.build_mesh(
        (("model", 2), ("data", -1)))) == {"model": 2, "data": 4}
    assert sharding.mesh_axes(sharding.build_mesh(
        {"data": 2, "model": 4})) == {"data": 2, "model": 4}
    cfg = sharding.MeshConfig.parse("data=8")
    assert sharding.mesh_axes(sharding.build_mesh(cfg)) == {"data": 8}


def test_build_mesh_errors():
    with pytest.raises(MXNetError, match="duplicate"):
        sharding.MeshConfig(("data", 2), ("data", 4))
    with pytest.raises(MXNetError, match="at most one"):
        sharding.MeshConfig(("a", -1), ("b", -1))
    with pytest.raises(MXNetError, match="not divisible"):
        sharding.build_mesh("data=-1,model=3")
    with pytest.raises(MXNetError, match="covers"):
        sharding.build_mesh("data=2,model=2")
    with pytest.raises(MXNetError, match="name=size"):
        sharding.MeshConfig.parse("data:4")


# ----------------------------------------------------------------------
# rule matching
# ----------------------------------------------------------------------
def test_rule_matching_first_hit_wins_and_explain():
    rules = sharding.PartitionRules([
        (r"_weight$", P("model", None)),
        (r"fc1_weight$", P(None, "model")),  # shadowed by the rule above
        (r"_bias$", P()),
    ], fallback=P(), name="t")
    params = {"fc1_weight": (8, 4), "fc1_bias": (8,), "gamma": (4,),
              "scalar": ()}
    specs = rules.match(params)
    assert specs["fc1_weight"] == P("model", None)
    assert specs["fc1_bias"] == P()
    assert specs["gamma"] == P()        # fallback
    assert specs["scalar"] == P()       # scalar short-circuit

    rows = {r["param"]: r for r in rules.explain(params)}
    assert rows["fc1_weight"]["rule"] == r"_weight$"
    assert rows["gamma"]["rule"] == "<fallback>"
    assert rows["scalar"]["rule"] == "<scalar>"
    table = rules.explain_str(params)
    assert "fc1_weight" in table and "<fallback>" in table


def test_unmatched_param_raises_with_name():
    rules = sharding.PartitionRules([(r"_weight$", P("model", None))])
    with pytest.raises(MXNetError, match="mystery_param"):
        rules.match({"mystery_param": (4, 4)})


def test_match_partition_rules_functional_and_presets():
    specs = sharding.match_partition_rules(
        [(r"w$", P("data"))], {"w": (8,), "b": (4,)}, fallback=P())
    assert specs == {"w": P("data"), "b": P()}
    mega = sharding.get_preset("transformer_megatron")
    specs = mega.match({"layer0_qkv_weight": (96, 32),
                        "layer0_proj_weight": (32, 32),
                        "layer0_ln1_gamma": (32,),
                        "lm_head_weight": (64, 32)})
    assert specs["layer0_qkv_weight"] == P("model", None)
    assert specs["layer0_proj_weight"] == P(None, "model")
    assert specs["layer0_ln1_gamma"] == P()
    assert specs["lm_head_weight"] == P("model", None)
    with pytest.raises(MXNetError, match="unknown partition-rule preset"):
        sharding.get_preset("nope")


def test_validate_specs_rejects_uneven_split():
    mesh = sharding.build_mesh("data=4,model=2")
    with pytest.raises(MXNetError, match="w1.*not divisible"):
        sharding.validate_specs(mesh, {"w1": P(None, "model")},
                                {"w1": (4, 7)})
    with pytest.raises(MXNetError, match="not a mesh axis"):
        sharding.validate_specs(mesh, {"w1": P("pipeline")}, {"w1": (8, 8)})


# ----------------------------------------------------------------------
# placement
# ----------------------------------------------------------------------
def test_shard_and_gather_roundtrip():
    from jax.sharding import NamedSharding

    mesh = sharding.build_mesh("data=4,model=2")
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    placed = sharding.shard_params(
        {"w": mx.nd.array(w), "b": mx.nd.ones((3,))},
        mesh, {"w": P("model", None)})
    jw = placed["w"]._data
    assert jw.sharding.is_equivalent_to(
        NamedSharding(mesh, P("model", None)), 2)
    assert {tuple(s.data.shape) for s in jw.addressable_shards} == {(4, 8)}
    host = sharding.gather_params(placed)
    np.testing.assert_array_equal(host["w"], w)
    np.testing.assert_array_equal(host["b"], np.ones(3, np.float32))


def test_place_is_noop_for_already_placed():
    import jax
    from jax.sharding import NamedSharding

    mesh = sharding.build_mesh("data=8")
    x = jax.device_put(np.ones((8, 4), np.float32),
                       NamedSharding(mesh, P("data", None)))
    assert sharding.place(x, mesh, P("data", None)) is x


def test_place_passes_through_equivalent_cross_process_stub():
    # single-process runs cannot create a real cross-process array, so a
    # duck-typed stand-in checks the no-op branch: an array that is NOT
    # fully addressable but already carries the target sharding must pass
    # through untouched instead of raising
    from jax.sharding import NamedSharding

    mesh = sharding.build_mesh("data=8")
    target = NamedSharding(mesh, P())

    class Stub:
        sharding = target
        committed = True
        ndim = 2
        shape = (4, 4)
        is_fully_addressable = False
        is_fully_replicated = False

    stub = Stub()
    assert sharding.place(stub, mesh, P()) is stub


def test_place_raises_for_true_cross_process_reshard():
    from jax.sharding import NamedSharding

    mesh = sharding.build_mesh("data=8")

    class Stub:
        sharding = NamedSharding(mesh, P("data", None))
        committed = True
        ndim = 2
        shape = (8, 4)
        is_fully_addressable = False
        is_fully_replicated = False

    with pytest.raises(MXNetError, match="cannot re-place"):
        sharding.place(Stub(), mesh, P(None, "data"))


def test_param_bytes_accounting():
    mesh = sharding.build_mesh("data=4,model=2")
    placed = sharding.shard_params(
        {"w": mx.nd.zeros((8, 8)), "r": mx.nd.zeros((8, 8))},
        mesh, {"w": P("model", None)})
    per_dev, repl = sharding.param_bytes(placed.values())
    assert repl == 2 * 8 * 8 * 4
    assert per_dev == 8 * 8 * 4 // 2 + 8 * 8 * 4  # w halved, r replicated


# ----------------------------------------------------------------------
# executor_group._replicate no-op (pre-sharded set_params)
# ----------------------------------------------------------------------
def test_exec_group_replicate_noop_for_placed_array():
    from jax.sharding import NamedSharding

    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup

    mesh = sharding.build_mesh("data=8")
    group = DataParallelExecutorGroup.__new__(DataParallelExecutorGroup)
    group._mesh = mesh
    group._repl_sharding = NamedSharding(mesh, P())
    group._multiprocess = False

    class Stub:  # cross-process-shaped array already replicated on the mesh
        sharding = NamedSharding(mesh, P())
        committed = True
        ndim = 1
        shape = (4,)
        is_fully_addressable = False
        is_fully_replicated = True

    stub = Stub()
    assert group._replicate(stub) is stub


# ----------------------------------------------------------------------
# sharded fused training
# ----------------------------------------------------------------------
def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


MLP_RULES = sharding.PartitionRules([
    (r"fc1_weight$", P("model", None)),
    (r"fc1_bias$", P("model")),
    (r"fc2_weight$", P(None, "model")),
], fallback=P(), name="mlp")


def _train(mod, batches, lr=0.1):
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": lr,
                                         "momentum": 0.9})
    for batch in batches:
        mod.forward_backward(batch)
        mod.update()
    args, auxs = mod.get_params()
    return ({k: v.asnumpy() for k, v in args.items()},
            {k: v.asnumpy() for k, v in auxs.items()})


def _batches(data_shape, label_shape, n, vocab=None):
    rng = np.random.RandomState(3)
    out = []
    for _ in range(n):
        if vocab:
            X = rng.randint(0, vocab, size=data_shape).astype(np.float32)
            y = rng.randint(0, vocab, size=label_shape).astype(np.float32)
        else:
            X = rng.randn(*data_shape).astype(np.float32)
            y = (rng.rand(*label_shape) * 8).astype(np.float32)
        out.append(mx.io.DataBatch(data=[mx.nd.array(X)],
                                   label=[mx.nd.array(y)]))
    return out


def _init_params(symbol, input_shapes):
    arg_shapes, _, aux_shapes = symbol.infer_shape(**input_shapes)
    rng = np.random.RandomState(11)
    args = {}
    inputs = set(input_shapes)
    for name, shape in zip(symbol.list_arguments(), arg_shapes):
        if name in inputs:
            continue
        args[name] = mx.nd.array(
            (rng.randn(*shape) * 0.05).astype(np.float32)) \
            if shape else mx.nd.zeros(shape)
    auxs = {}
    for name, shape in zip(symbol.list_auxiliary_states(), aux_shapes):
        auxs[name] = mx.nd.zeros(shape)
    return args, auxs


def test_mlp_sharded_fused_step_matches_single_device():
    # _init_params is deterministic; build a fresh dict per module (the
    # donated fused step consumes the buffers it is handed)
    shapes = {"data": (16, 64), "softmax_label": (16,)}
    batches = _batches((16, 64), (16,), 3)

    ref = mx.mod.Module(_mlp(), context=mx.cpu())
    ref.bind(data_shapes=[("data", (16, 64))],
             label_shapes=[("softmax_label", (16,))])
    ref.set_params(*_init_params(_mlp(), shapes))
    want_args, _ = _train(ref, batches)

    mesh = sharding.build_mesh("data=-1,model=2")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 64))],
             label_shapes=[("softmax_label", (16,))],
             mesh=mesh, partition_rules=MLP_RULES)
    mod.set_params(*_init_params(_mlp(), shapes))
    got_args, _ = _train(mod, batches)

    for name in want_args:
        np.testing.assert_allclose(got_args[name], want_args[name],
                                   rtol=2e-4, atol=2e-5, err_msg=name)
    # the layout really shards: fc1_weight lives in (16, 64) halves
    w = mod._exec_group.execs[0].arg_dict["fc1_weight"]._data
    assert {tuple(s.data.shape) for s in w.addressable_shards} == {(16, 64)}


def _tiny_lm():
    from mxnet_tpu.models.transformer import get_transformer_lm

    return get_transformer_lm(vocab_size=64, num_layers=1, num_heads=2,
                              hidden=32, seq_len=16, block_q=16, block_k=16)


def test_transformer_megatron_2d_mesh_matches_single_device():
    """Acceptance: 2-D ("data","model") megatron-ruled transformer LM step
    == single-device baseline (fp32), with per-device param bytes
    measurably below replicated (asserted via the telemetry gauges)."""
    import mxnet_tpu.telemetry as telemetry

    net = _tiny_lm()
    shapes = {"data": (8, 16), "softmax_label": (8, 16)}
    batches = _batches((8, 16), (8, 16), 2, vocab=64)

    ref = mx.mod.Module(net, context=mx.cpu())
    ref.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8, 16))])
    ref.set_params(*_init_params(net, shapes))
    want_args, _ = _train(ref, batches, lr=0.05)

    telemetry._reset_for_tests()
    telemetry.enable()
    try:
        mesh = sharding.build_mesh("data=-1,model=2")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=[("data", (8, 16))],
                 label_shapes=[("softmax_label", (8, 16))],
                 mesh=mesh, partition_rules="transformer_megatron")
        mod.set_params(*_init_params(net, shapes))
        got_args, _ = _train(mod, batches, lr=0.05)

        snap = telemetry.registry().snapshot()
        sharded = snap.get("mxtpu_params_sharded_bytes")
        repl = snap.get("mxtpu_params_replicated_bytes")
        assert sharded and repl and sharded < repl
        assert telemetry.summary()["step"]["mesh"] == {"data": 4, "model": 2}
    finally:
        telemetry._reset_for_tests()

    for name in want_args:
        np.testing.assert_allclose(got_args[name], want_args[name],
                                   rtol=5e-4, atol=5e-5, err_msg=name)
    # tensor parallelism is real: the qkv weight is split across 'model'
    w = mod._exec_group.execs[0].arg_dict["layer0_qkv_weight"]._data
    assert {tuple(s.data.shape) for s in w.addressable_shards} == {(48, 32)}


def test_default_path_unchanged_without_rules():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 64))],
             label_shapes=[("softmax_label", (8,))])
    assert mod._exec_group._rules is None
    assert mod._exec_group._mesh is None  # single ctx, no env knobs


def test_env_var_activation(monkeypatch):
    monkeypatch.setenv("MXNET_SHARDING_MESH", "data=-1,model=2")
    monkeypatch.setenv("MXNET_SHARDING_RULES", "replicated")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 64))],
             label_shapes=[("softmax_label", (8,))])
    group = mod._exec_group
    assert group._rules is not None and group._rules.name == "replicated"
    assert sharding.mesh_axes(group._mesh) == {"data": 4, "model": 2}


def test_bind_rejects_uneven_rule_split():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=7, name="odd")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rules = sharding.PartitionRules([(r"odd_weight$", P("model", None))],
                                    fallback=P())
    mod = mx.mod.Module(net, context=mx.cpu())
    with pytest.raises(MXNetError, match="odd_weight"):
        mod.bind(data_shapes=[("data", (8, 64))],
                 label_shapes=[("softmax_label", (8,))],
                 mesh="data=-1,model=2",
                 partition_rules=rules)

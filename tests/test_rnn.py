"""RNN stack: fused RNN op, cell library, fused-vs-unfused oracle, bucketing
iterator (reference: tests/python/unittest/test_rnn.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.ops.rnn_op import rnn_param_size
from mxnet_tpu.test_utils import assert_almost_equal


@pytest.mark.parametrize("mode,nstate", [("rnn_relu", 1), ("rnn_tanh", 1),
                                         ("lstm", 2), ("gru", 1)])
def test_rnn_op_shapes(mode, nstate):
    T, N, I, H, L = 7, 4, 5, 6, 2
    ps = rnn_param_size(I, H, L, mode, True)
    kwargs = dict(state_size=H, num_layers=L, bidirectional=True, mode=mode,
                  state_outputs=True)
    ins = dict(data=nd.array(np.random.randn(T, N, I).astype(np.float32)),
               parameters=nd.array(
                   0.1 * np.random.randn(ps).astype(np.float32)),
               state=nd.zeros((L * 2, N, H)))
    if mode == "lstm":
        ins["state_cell"] = nd.zeros((L * 2, N, H))
    outs = nd.RNN(**ins, **kwargs)
    outs = outs if isinstance(outs, list) else [outs]
    assert outs[0].shape == (T, N, 2 * H)
    assert outs[1].shape == (L * 2, N, H)
    if mode == "lstm":
        assert outs[2].shape == (L * 2, N, H)


@pytest.mark.parametrize("mode", ["rnn_tanh", "lstm", "gru"])
def test_fused_vs_unfused(mode):
    """FusedRNNCell (lax.scan kernel) must match its unfuse()d stack of
    python cells, through pack/unpack weight conversion."""
    T, N, I, H, L = 5, 3, 4, 6, 2
    fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode=mode,
                                prefix="%s_" % mode)
    data = sym.Variable("data")
    fused_out, _ = fused.unroll(T, inputs=data, layout="NTC",
                                merge_outputs=True)

    stack = fused.unfuse()
    unfused_out, _ = stack.unroll(T, inputs=data, layout="NTC",
                                  merge_outputs=True)

    x = np.random.randn(N, T, I).astype(np.float32)
    ps = rnn_param_size(I, H, L, mode)
    packed = 0.2 * np.random.randn(ps).astype(np.float32)
    fused_args = {"data": nd.array(x),
                  "%s_parameters" % mode: nd.array(packed)}
    exe_f = fused_out.bind(mx.cpu(), fused_args)
    out_f = exe_f.forward()[0].asnumpy()
    assert out_f.shape == (N, T, H)

    unpacked = fused.unpack_weights({"%s_parameters" % mode: packed})
    unfused_args = {"data": nd.array(x)}
    for k, v in unpacked.items():
        unfused_args[k] = nd.array(v)
    exe_u = unfused_out.bind(mx.cpu(), unfused_args)
    out_u = exe_u.forward()[0].asnumpy()
    assert_almost_equal(out_f, out_u, rtol=1e-4, atol=1e-5)

    # pack round-trips
    repacked = fused.pack_weights(unpacked)
    np.testing.assert_allclose(repacked["%s_parameters" % mode], packed,
                               rtol=1e-6)


def test_fused_bidirectional_vs_unfused():
    T, N, I, H = 4, 2, 3, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm",
                                bidirectional=True, prefix="bi_")
    data = sym.Variable("data")
    fused_out, _ = fused.unroll(T, inputs=data, layout="NTC",
                                merge_outputs=True)
    stack = fused.unfuse()
    unfused_out, _ = stack.unroll(T, inputs=data, layout="NTC",
                                  merge_outputs=True)

    x = np.random.randn(N, T, I).astype(np.float32)
    ps = rnn_param_size(I, H, 1, "lstm", True)
    packed = 0.2 * np.random.randn(ps).astype(np.float32)
    exe_f = fused_out.bind(mx.cpu(), {"data": nd.array(x),
                                      "bi_parameters": nd.array(packed)})
    out_f = exe_f.forward()[0].asnumpy()
    assert out_f.shape == (N, T, 2 * H)

    unpacked = fused.unpack_weights({"bi_parameters": packed})
    args = {"data": nd.array(x)}
    args.update({k: nd.array(v) for k, v in unpacked.items()})
    exe_u = unfused_out.bind(mx.cpu(), args)
    out_u = exe_u.forward()[0].asnumpy()
    assert_almost_equal(out_f, out_u, rtol=1e-4, atol=1e-5)


def test_rnn_gradients_flow():
    """Gradient through the fused kernel reaches data and parameters."""
    T, N, I, H = 3, 2, 4, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="g_")
    data = sym.Variable("data")
    out, _ = fused.unroll(T, inputs=data, layout="NTC", merge_outputs=True)
    loss = sym.MakeLoss(sym.sum(out * out))
    x = np.random.randn(N, T, I).astype(np.float32)
    ps = rnn_param_size(I, H, 1, "lstm")
    packed = 0.1 * np.random.randn(ps).astype(np.float32)
    args = {"data": nd.array(x), "g_parameters": nd.array(packed)}
    grads = {k: nd.zeros(v.shape) for k, v in args.items()}
    exe = loss.bind(mx.cpu(), args, args_grad=grads)
    exe.forward(is_train=True)
    exe.backward()
    assert np.abs(exe.grad_dict["g_parameters"].asnumpy()).sum() > 0
    assert np.abs(exe.grad_dict["data"].asnumpy()).sum() > 0


def test_cell_unroll_shapes():
    cell = mx.rnn.LSTMCell(10, prefix="l_")
    outputs, states = cell.unroll(3, input_prefix="t_")
    assert len(outputs) == 3
    assert len(states) == 2
    _, out_shapes, _ = mx.sym.Group(outputs).infer_shape(
        t_t0_data=(2, 7), t_t1_data=(2, 7), t_t2_data=(2, 7))
    assert all(tuple(s) == (2, 10) for s in out_shapes)


def test_sequential_stack():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.LSTMCell(8, prefix="l1_"))
    data = sym.Variable("data")
    out, states = stack.unroll(4, inputs=data, layout="NTC",
                               merge_outputs=True)
    assert len(states) == 4
    x = np.random.randn(2, 4, 6).astype(np.float32)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(2, 4, 6))
    assert tuple(out_shapes[0]) == (2, 4, 8)


def test_residual_and_dropout_cells():
    base = mx.rnn.RNNCell(6, prefix="r_")
    cell = mx.rnn.ResidualCell(base)
    data = sym.Variable("data")
    out, _ = cell.unroll(3, inputs=data, layout="NTC", merge_outputs=True)
    _, out_shapes, _ = out.infer_shape(data=(2, 3, 6))
    assert tuple(out_shapes[0]) == (2, 3, 6)

    d = mx.rnn.DropoutCell(0.5)
    o, s = d(sym.Variable("x"), [])
    assert s == []


def test_zoneout_cell():
    base = mx.rnn.RNNCell(5, prefix="z_")
    cell = mx.rnn.ZoneoutCell(base, zoneout_outputs=0.3, zoneout_states=0.3)
    data = sym.Variable("data")
    out, _ = cell.unroll(3, inputs=data, layout="NTC", merge_outputs=True)
    _, out_shapes, _ = out.infer_shape(data=(2, 3, 5))
    assert tuple(out_shapes[0]) == (2, 3, 5)


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [1, 1, 1], [2, 2],
                 [3, 3, 3, 3]] * 4
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[3, 5], invalid_label=0)
    seen = 0
    for batch in it:
        assert batch.bucket_key in (3, 5)
        assert batch.data[0].shape == (4, batch.bucket_key)
        assert batch.label[0].shape == (4, batch.bucket_key)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])
        seen += 1
    assert seen >= 2
    it.reset()
    assert sum(1 for _ in it) == seen

"""Autoscaler + replica-registry tests.

The control-loop state machine (hysteresis, cooldown, band clamping,
signal classification) runs on a fake clock against a fake router — no
real sleeps, every decision deterministic.  The registry tests cover
the membership contract (generations, heartbeats, stale eviction) and
its HTTP face; the chaos-marked acceptance test replays the
``flash-crowd`` scenario from tools/chaos_run.py end to end.
"""
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IN_DIM = 6
HID = 3


# -- fakes: the state machine needs signals, not servers ---------------------
class FakeRouter:
    def __init__(self):
        self.sig = dict(pressure=0.0, replicas=1, ready=1, draining=0,
                        breakers_open=0, shed_total=0, expired_total=0,
                        p99_ms={}, deadline_ms={})
        self.added = []
        self.removed = []

    def signals(self):
        return dict(self.sig)

    def add_replica(self, backend, name=None):
        self.added.append(name)
        self.sig["replicas"] += 1
        return name

    def remove_replica(self, name, drain=True, drain_timeout_ms=None,
                       wait=True):
        self.removed.append(name)
        self.sig["replicas"] -= 1
        return "backend"

    def describe(self):
        return [{"name": n, "draining": False, "inflight": 0,
                 "queue_depth": 0}
                for n in self.added if n not in self.removed]


class FakeProvider:
    self_registering = False

    def __init__(self):
        self.n = 0
        self.retired = []

    def spawn(self):
        self.n += 1
        return "a%d" % self.n, object()

    def retire(self, name, backend):
        self.retired.append(name)


def _scaler(router, provider, clock, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("cooldown_ms", 1000)
    return serving.Autoscaler(router, provider, clock=clock, **kw)


def test_autoscaler_hysteresis_on_fake_clock():
    """One hot tick must not spawn; K consecutive ticks must.  A cold
    tick in between resets the streak."""
    t = [0.0]
    r, p = FakeRouter(), FakeProvider()
    asc = _scaler(r, p, lambda: t[0], hysteresis=3)
    r.sig["pressure"] = 0.9
    assert asc.tick() is None
    assert asc.tick() is None
    r.sig["pressure"] = 0.2          # back to normal: streak resets
    assert asc.tick() is None
    r.sig["pressure"] = 0.9
    assert asc.tick() is None
    assert asc.tick() is None
    ev = asc.tick()                  # third consecutive hot tick
    assert ev["op"] == "scale_out" and ev["ok"]
    assert r.added == ["a1"]
    assert "pressure" in ev["why"]


def test_autoscaler_cooldown_on_fake_clock():
    """After an actuation no decision fires inside the cooldown window,
    however hot the signals; the first tick past the window may."""
    t = [0.0]
    r, p = FakeRouter(), FakeProvider()
    asc = _scaler(r, p, lambda: t[0], cooldown_ms=1000)
    r.sig["pressure"] = 1.0
    asc.tick()
    assert asc.tick()["op"] == "scale_out"
    for _ in range(20):              # still t=0: deep in cooldown
        assert asc.tick() is None
    t[0] = 0.999
    assert asc.tick() is None
    t[0] = 1.001                     # window over; streak long satisfied
    assert asc.tick()["op"] == "scale_out"
    assert len(r.added) == 2


def test_autoscaler_band_and_ownership():
    """Never spawns above MAX; never drains below MIN; never retires a
    replica it did not spawn (the seed fleet is the operator's)."""
    t = [0.0]
    r, p = FakeRouter(), FakeProvider()
    asc = _scaler(r, p, lambda: t[0], max_replicas=2, cooldown_ms=100)
    r.sig["pressure"] = 1.0
    asc.tick()
    assert asc.tick()["op"] == "scale_out"
    t[0] = 1.0
    for _ in range(5):
        assert asc.tick() is None    # at MAX: hot ticks do nothing
    assert r.sig["replicas"] == 2
    r.sig["pressure"] = 0.0
    t[0] = 2.0
    asc.tick()
    ev = asc.tick()
    assert ev["op"] == "scale_in" and ev["replica"] == "a1"
    assert p.retired == ["a1"]
    t[0] = 3.0
    for _ in range(5):
        assert asc.tick() is None    # at MIN, and the seed is not ours
    assert r.sig["replicas"] == 1 and r.removed == ["a1"]


def test_autoscaler_slo_breaker_and_shed_votes():
    """Every documented overload signal votes scale-out: p99 over the
    deadline budget, an open breaker, and a positive shed delta."""
    def keep_shedding(sig):
        sig["shed_total"] += 5       # sheds keep landing every tick

    for hot in (lambda sig: sig.update(p99_ms={"interactive": 90.0},
                                       deadline_ms={"interactive": 50.0}),
                lambda sig: sig.update(breakers_open=1),
                keep_shedding):
        t = [0.0]
        r, p = FakeRouter(), FakeProvider()
        asc = _scaler(r, p, lambda: t[0])
        asc.tick()                   # baseline tick (shed delta needs one)
        hot(r.sig)
        asc.tick()
        hot(r.sig)
        ev = asc.tick()
        assert ev is not None and ev["op"] == "scale_out", hot
    # p99 UNDER budget is not a vote
    t = [0.0]
    r, p = FakeRouter(), FakeProvider()
    asc = _scaler(r, p, lambda: t[0])
    r.sig.update(p99_ms={"interactive": 30.0},
                 deadline_ms={"interactive": 50.0}, pressure=0.2)
    for _ in range(5):
        assert asc.tick() is None


def test_autoscaler_decisions_are_fault_injectable():
    """An injected fault on the dotted scale-out op surfaces as a failed
    (but logged) decision; the loop survives and succeeds once clear."""
    t = [0.0]
    r, p = FakeRouter(), FakeProvider()
    asc = _scaler(r, p, lambda: t[0], cooldown_ms=100)
    r.sig["pressure"] = 1.0
    with mx.faults.inject("serving.autoscaler.scale_out:ioerr=1", seed=0):
        asc.tick()
        ev = asc.tick()
        assert ev["op"] == "scale_out" and not ev["ok"]
        assert "error" in ev
    assert r.added == []             # the actuation never happened
    t[0] = 1.0
    asc.tick()
    assert asc.tick()["ok"]          # fault cleared: next attempt lands


# -- the registry: membership contract ---------------------------------------
def test_registry_generations_and_heartbeat():
    reg = serving.ReplicaRegistry(ttl_ms=60000)
    g0 = reg.gen()
    g1 = reg.register("a", "127.0.0.1:1", {"v": 1})
    assert g1 == g0 + 1
    assert reg.register("a", "127.0.0.1:1") == g1   # refresh: no gen bump
    assert reg.heartbeat("a") and not reg.heartbeat("ghost")
    live = reg.live()
    assert live["gen"] == g1 and live["replicas"] == {"a": "127.0.0.1:1"}
    g2 = reg.deregister("a")
    assert g2 == g1 + 1 and reg.live()["replicas"] == {}
    assert reg.deregister("a") == g2                # idempotent


def test_registry_stale_eviction():
    reg = serving.ReplicaRegistry(ttl_ms=80)
    reg.register("fast", "x")
    reg.register("dead", "y")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        reg.heartbeat("fast")
        if set(reg.live()["replicas"]) == {"fast"}:
            break
        time.sleep(0.02)
    assert set(reg.live()["replicas"]) == {"fast"}


def test_registry_http_face_roundtrip():
    reg = serving.ReplicaRegistry(ttl_ms=60000)
    try:
        reg.serve_http()
        cli = serving.RegistryClient(reg.addr)
        g = cli.register("web", "127.0.0.1:9")
        assert cli.live()["replicas"] == {"web": "127.0.0.1:9"}
        assert cli.gen() == g
        assert cli.heartbeat("web") and not cli.heartbeat("ghost")
        cli.deregister("web")
        assert cli.live()["replicas"] == {}
        with pytest.raises(Exception):  # object backends cannot cross HTTP
            cli.register("bad", {"not": "a string"})
    finally:
        reg.close()


def test_start_heartbeater_reregisters_after_eviction():
    reg = serving.ReplicaRegistry(ttl_ms=150)
    stop = serving.start_heartbeater(reg, "r0", "b", interval_ms=30)
    try:
        time.sleep(0.4)              # several TTLs: beats must hold it live
        assert "r0" in reg.live()["replicas"]
    finally:
        stop()
    assert "r0" not in reg.live()["replicas"]   # stop() deregistered


# -- serving preemption handler (shared retirement path) ---------------------
def _tiny_server(**kw):
    rng = np.random.RandomState(0)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=HID,
                                name="fc")
    params = {"fc_weight": mx.nd.array(
                  rng.randn(HID, IN_DIM).astype(np.float32)),
              "fc_bias": mx.nd.array(rng.randn(HID).astype(np.float32))}
    kw.setdefault("max_wait_us", 1000)
    kw.setdefault("warmup", False)
    return serving.InferenceServer(net, params, {"data": (4, IN_DIM)}, **kw)


def test_serving_preemption_handler_drains_and_deregisters():
    """SIGTERM path: drain (readyz flips 503 first), deregister, stop —
    idempotent on repeated signals, and no process exit in test mode."""
    srv = _tiny_server()
    calls = []
    handler = serving.install_preemption_handler(
        srv, deregister=lambda: calls.append("dereg"), exit_process=False)
    fut = srv.submit(data=np.zeros(IN_DIM, np.float32))
    handler(signal.SIGTERM, None)
    assert calls == ["dereg"]
    assert srv.ready_state() == "stopped"
    assert fut.result(timeout=10) is not None   # drained, not dropped
    handler(signal.SIGTERM, None)               # idempotent
    assert calls == ["dereg"]


@pytest.mark.chaos
def test_flash_crowd_end_to_end():
    """Acceptance: diurnal + flash-crowd load over a replicated front
    door; the fleet scales 1→N→1, one router dies mid-flood, zero failed
    requests, zero interactive-SLO violations, and every scaled-out
    replica's first request runs with cold_bucket_runs()==0."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from chaos_run import run_flash_crowd

    assert run_flash_crowd(seed=3, timeout=90.0)

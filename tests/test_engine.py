"""Engine-vs-serial-oracle randomized testing.

TPU mapping of the reference's dependency-engine correctness harness
(tests/cpp/threaded_engine_test.cc:19-40: random read/write workloads
replayed against all engines + a serial oracle). Here the "threaded
engine" is JAX async dispatch + jit, and the serial oracle is
``MXNET_ENGINE_TYPE=NaiveEngine`` (jit disabled, sync after every op) —
both must produce identical program results for random workloads.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _random_imperative_workload(seed, backend):
    """Run the SAME random op sequence (incl. in-place mutation, the
    engine's write-dependency case) on the nd path or a numpy serial
    oracle; return the final pool."""
    rng = np.random.RandomState(seed)
    init = [rng.randn(4, 5).astype(np.float32) for _ in range(4)]
    if backend == "nd":
        pool = [mx.nd.array(a) for a in init]
        dot, tanh = mx.nd.dot, mx.nd.tanh
    else:
        pool = [a.copy() for a in init]
        dot, tanh = np.dot, np.tanh
    for _ in range(30):
        op = rng.randint(6)
        i, j = rng.randint(len(pool)), rng.randint(len(pool))
        if op == 0:
            pool[i] = pool[i] + pool[j]
        elif op == 1:
            pool[i] = pool[i] * 0.5 + pool[j] * 0.25
        elif op == 2:
            pool[i][:] = pool[j]  # in-place write (engine write-dep)
        elif op == 3:
            pool[i] += pool[j]  # read+write same var
        elif op == 4:
            pool[i] = dot(dot(pool[i], pool[j].T), pool[j])
        else:
            pool[i] = tanh(pool[j])
    return [a.asnumpy() if backend == "nd" else a for a in pool]


def _random_graph_workload(seed):
    """Forward+backward on a randomly composed small graph."""
    rng = np.random.RandomState(seed)
    net = mx.sym.Variable("data")
    dims = [6]
    for k in range(rng.randint(2, 4)):
        h = int(rng.randint(3, 8))
        net = mx.sym.FullyConnected(net, num_hidden=h, name="fc%d" % k)
        act = ["relu", "tanh", "sigmoid"][rng.randint(3)]
        net = mx.sym.Activation(net, act_type=act)
        dims.append(h)
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(net, num_hidden=3,
                                                     name="fco"),
                               name="softmax")
    shapes = {"data": (5, 6), "softmax_label": (5,)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    args = {}
    r2 = np.random.RandomState(seed + 1)
    for name, shp in zip(net.list_arguments(), arg_shapes):
        args[name] = mx.nd.array(r2.randn(*shp).astype(np.float32) * 0.4)
    args["softmax_label"] = mx.nd.array((np.arange(5) % 3).astype(np.float32))
    grads = {n: mx.nd.zeros(s) for n, s in zip(net.list_arguments(),
                                               arg_shapes)
             if n not in shapes}
    ex = net.bind(mx.cpu(), args, args_grad=grads)
    ex.forward(is_train=True)
    ex.backward()
    out = {"out": ex.outputs[0].asnumpy()}
    out.update({k: v.asnumpy() for k, v in ex.grad_dict.items()})
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_async_engine_matches_serial_numpy_oracle(seed, monkeypatch):
    """Async-dispatch nd path vs a pure-numpy SERIAL oracle of the same
    random workload (the reference harness's oracle is serial execution),
    then again with NaiveEngine sync-after-every-op enabled."""
    oracle = _random_imperative_workload(seed, "np")
    fast = _random_imperative_workload(seed, "nd")
    for a, b in zip(fast, oracle):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    naive = _random_imperative_workload(seed, "nd")
    for a, b in zip(naive, oracle):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_jit_executor_matches_naive_oracle_graph(seed, monkeypatch):
    fast = _random_graph_workload(seed)
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    slow = _random_graph_workload(seed)
    assert fast.keys() == slow.keys()
    for k in fast:
        np.testing.assert_allclose(fast[k], slow[k], rtol=1e-4, atol=1e-5,
                                   err_msg="engine/oracle divergence at %s"
                                           % k)

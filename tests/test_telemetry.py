"""Unified telemetry subsystem (mxnet_tpu.telemetry): registry/renderer
basics, the cross-layer merged Chrome trace, the StepMonitor MFU path, the
recompile detector, the comm_stats/serving registry folds, the real-tid
profiler satellite, and the telemetry-off overhead guard."""
import json
import os
import subprocess
import sys
import threading
import time
import timeit
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu import profiler as prof
from mxnet_tpu.comm_engine import make_async

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry._reset_for_tests()
    yield
    telemetry._reset_for_tests()


def _fit_small(epochs=1, bs=10, n=50, speedometer=None, seed=0):
    rng = np.random.RandomState(seed)
    data = rng.uniform(size=(n, 10)).astype(np.float32)
    label = rng.randint(0, 2, (n,)).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=bs)
    x = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, label_names=("softmax_label",))
    cbs = [speedometer] if speedometer is not None else None
    mod.fit(it, num_epoch=epochs, batch_end_callback=cbs,
            optimizer_params={"learning_rate": 0.1})
    return mod, it


# ---------------------------------------------------------------------------
# registry + renderer
# ---------------------------------------------------------------------------
def test_registry_instruments_and_prometheus_render():
    telemetry.enable(trace=False)
    c = telemetry.counter("mxtpu_t_total", "doc")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = telemetry.gauge("mxtpu_t_gauge")
    g.set(7)
    g.set_max(3)  # set_max never lowers
    assert g.value == 7
    h = telemetry.histogram("mxtpu_t_ms", start=1.0, factor=2.0, count=3)
    for v in (0.5, 3.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 3 and snap["sum"] == pytest.approx(103.5)
    lc = telemetry.labeled_counter("mxtpu_t_kinds", "kind")
    lc.inc("a")
    lc.inc("a")
    lc.inc("b")
    assert lc.get("a") == 2

    text = telemetry.render_prometheus()
    assert "# TYPE mxtpu_t_total counter" in text
    assert "mxtpu_t_total 5" in text
    assert "mxtpu_t_gauge 7" in text
    assert 'mxtpu_t_ms_bucket{le="+Inf"} 3' in text
    assert 'mxtpu_t_kinds{kind="a"} 2' in text
    # same name, wrong type is a hard error, not silent aliasing
    with pytest.raises(TypeError):
        telemetry.gauge("mxtpu_t_total")


def test_event_log_ring_and_jsonl(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY_DIR", str(tmp_path))
    telemetry.enable(trace=False)
    telemetry.log_event("alpha", x=1)
    telemetry.log_event("beta", y="z")
    evs = telemetry.events()
    assert [e["kind"] for e in evs] == ["alpha", "beta"]
    assert all("ts" in e for e in evs)
    path = tmp_path / "events.jsonl"
    telemetry.disable()  # flush/close
    lines = [json.loads(ln) for ln in path.read_text().splitlines() if ln]
    assert [e["kind"] for e in lines] == ["alpha", "beta"]
    assert lines[0]["x"] == 1


def test_log_event_noop_when_disabled():
    assert not telemetry.enabled()
    assert telemetry.log_event("nope") is None
    assert telemetry.events() == []


# ---------------------------------------------------------------------------
# acceptance: ONE merged trace, spans from every layer on named tracks
# ---------------------------------------------------------------------------
def test_merged_trace_spans_all_layers(tmp_path):
    """Short training run + comm-engine traffic + a serving batch: the
    merged Chrome trace holds training-step, comm-engine and serving spans
    on distinct thread tracks, schema-valid, with thread_name metadata."""
    from mxnet_tpu import serving

    telemetry.enable(trace=True)

    _fit_small()  # 'fit' + 'exec' spans on the main thread

    kv = make_async(mx.kv.create("local"), num_threads=2, bucket_bytes=0)
    try:
        kv.init(1, nd.ones((8,)))
        kv.push(1, nd.ones((8,)))
        out = nd.zeros((8,))
        kv.pull(1, out)
        kv.wait()
    finally:
        kv.close()

    rng = np.random.RandomState(0)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                name="fc")
    params = {"fc_weight": mx.nd.array(rng.randn(3, 6).astype(np.float32)),
              "fc_bias": mx.nd.array(rng.randn(3).astype(np.float32))}
    srv = serving.InferenceServer(net, params, {"data": (4, 6)},
                                  max_wait_us=1000, max_queue=16)
    try:
        srv.submit(data=rng.randn(6).astype(np.float32)).result(5)
    finally:
        srv.stop(drain=True)

    payload = telemetry.merged_trace()
    telemetry.validate_trace(payload)
    spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    by_cat = {}
    for e in spans:
        by_cat.setdefault(e.get("cat"), set()).add(e["tid"])
    assert "fit" in by_cat, by_cat.keys()
    assert "comm" in by_cat, by_cat.keys()
    assert "serving" in by_cat, by_cat.keys()
    # distinct thread tracks: comm-engine workers and the serving batcher
    # are their own threads, not the training main thread
    assert not (by_cat["fit"] & by_cat["comm"])
    assert not (by_cat["fit"] & by_cat["serving"])
    # every span's tid has a thread_name metadata record
    named = {e["tid"]: e["args"]["name"]
             for e in payload["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    for e in spans:
        assert e["tid"] in named
    assert any("comm" in v for v in named.values())

    out = tmp_path / "merged.json"
    telemetry.dump_trace(str(out))
    reloaded = json.loads(out.read_text())
    telemetry.validate_trace(reloaded)
    assert len(reloaded["traceEvents"]) == len(payload["traceEvents"])


# ---------------------------------------------------------------------------
# StepMonitor: counters, MFU parity with the probe path, memory/report
# ---------------------------------------------------------------------------
def test_step_monitor_counts_and_report():
    telemetry.enable(trace=False)
    mod, _ = _fit_small(bs=10, n=50)
    mon = telemetry.current_step_monitor()
    assert mon is not None
    assert mon.c_steps.value == 5
    assert mon.c_samples.value == 50
    rep = mon.report()
    assert rep["steps"] == 5
    assert rep["avg_step_ms"] and rep["avg_step_ms"] > 0
    assert rep["data_wait_ms_total"] >= 0
    assert rep["samples_per_sec"] and rep["samples_per_sec"] > 0
    summ = telemetry.summary()
    assert summ["counters"]["mxtpu_steps_total"] == 5
    assert summ["step"]["steps"] == 5


def test_step_monitor_mfu_matches_probe_path():
    """The monitor's flop count is the XLA cost analysis of the SAME
    compiled executable tools/perf_probe.py lowers — parity within 10%
    (exact, in practice) by construction."""
    telemetry.enable(trace=False)
    mod, _ = _fit_small()
    mon = telemetry.current_step_monitor()
    assert mon.c_compiles.value >= 1
    ex = mod._exec_group.execs[0]
    info = telemetry.fused_cost_analysis(ex)
    if info is None or not info.get("flops"):
        pytest.skip("backend exposes no cost analysis")
    assert mon.flops_per_step == pytest.approx(info["flops"], rel=0.10)
    mfu = mon.mfu()
    assert mfu is not None
    expect = info["flops"] / mon.avg_step_s() / telemetry.peak_flops()
    assert mfu == pytest.approx(expect, rel=0.10)


def test_peak_flops_override(monkeypatch):
    assert telemetry.peak_flops() == 197e12
    monkeypatch.setenv("MXNET_TELEMETRY_PEAK_FLOPS", "1e12")
    assert telemetry.peak_flops() == 1e12


# ---------------------------------------------------------------------------
# recompile detector
# ---------------------------------------------------------------------------
def test_recompile_detector_fires_exactly_once_per_new_shape():
    telemetry.enable(trace=False)
    mod, _ = _fit_small(bs=10, n=50)
    mon = telemetry.current_step_monitor()
    assert mon.c_recompiles.value == 0  # constant shapes: silent

    rng = np.random.RandomState(1)
    data9 = rng.uniform(size=(45, 10)).astype(np.float32)
    label9 = rng.randint(0, 2, (45,)).astype(np.float32)
    it9 = mx.io.NDArrayIter(data9, label9, batch_size=9)
    batch = next(iter(it9))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mod.forward_backward(batch)  # batch 10 -> 9: NEW signature
        mod.forward_backward(batch)  # same signature again: no new warning
    rws = [x for x in w if issubclass(x.category, telemetry.RecompileWarning)]
    assert len(rws) == 1
    assert "10" in str(rws[0].message) and "9" in str(rws[0].message)
    assert mon.c_recompiles.value == 1
    assert any(e["kind"] == "recompile" for e in telemetry.events())


def test_recompile_detector_silent_across_epochs():
    """Epoch boundaries replay the SAME shapes — never a recompile."""
    telemetry.enable(trace=False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mod, _ = _fit_small(epochs=3)  # keep the module (it owns the monitor)
    assert not [x for x in w
                if issubclass(x.category, telemetry.RecompileWarning)]
    assert telemetry.current_step_monitor().c_recompiles.value == 0
    assert mod is not None


# ---------------------------------------------------------------------------
# overhead guard: telemetry off must stay near-free
# ---------------------------------------------------------------------------
def test_disabled_overhead_under_two_percent():
    """Off, each hook site costs one module-global bool read.  Budget:
    ~12 hook reads per step must stay under 2% of even a tiny CPU step."""
    assert not telemetry.enabled()
    mod, it = _fit_small()  # telemetry off: fit runs the plain path
    assert telemetry.current_step_monitor() is None  # no monitor was built

    # measured cost of one gate read, amortized over 200k calls
    n = 200_000
    per_gate_s = timeit.timeit(telemetry.enabled, number=n) / n

    # measured steady-state step time for the same tiny module
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)
    mod.update()
    t0 = time.perf_counter()
    for _ in range(20):
        mod.forward_backward(batch)
        mod.update()
    step_s = (time.perf_counter() - t0) / 20

    hooks_per_step = 12  # fit fetch + fwd/bwd + update + iterator + comm
    assert per_gate_s * hooks_per_step < 0.02 * step_s, \
        "telemetry-off gate cost %.3fus x %d vs step %.1fus" % (
            per_gate_s * 1e6, hooks_per_step, step_s * 1e6)


# ---------------------------------------------------------------------------
# satellites: profiler tids + mid-run flush, comm_stats fold, serving fold
# ---------------------------------------------------------------------------
def test_profiler_records_real_thread_ids(tmp_path):
    out = tmp_path / "prof.json"
    mx.profiler.profiler_set_config(mode="all", filename=str(out))
    mx.profiler.profiler_set_state("run")
    try:
        with prof.Frame("main.span", "test"):
            pass

        def worker():
            with prof.Frame("worker.span", "test"):
                pass

        t = threading.Thread(target=worker, name="tele-test-worker")
        t.start()
        t.join()
        # satellite: dump_profile flushes mid-run, without stop
        mx.profiler.dump_profile()
    finally:
        mx.profiler.profiler_set_state("stop")
    events = json.loads(out.read_text())["traceEvents"]
    mine = [e for e in events if e["name"].endswith(".span")]
    assert len(mine) == 2
    tids = {e["tid"] for e in mine}
    assert len(tids) == 2  # real per-thread ids, not the old constant 0
    assert all(e["ph"] == "X" and "dur" in e for e in events)


def test_comm_stats_is_view_over_registry():
    telemetry.enable(trace=False)
    kv = make_async(mx.kv.create("local"), num_threads=1, bucket_bytes=0)
    try:
        kv.init(7, nd.ones((4,)))
        kv.push(7, nd.ones((4,)))
        out = nd.zeros((4,))
        kv.pull(7, out)
        kv.wait()
        stats = kv.comm_stats()
        # the dict API is unchanged...
        for key in ("pushes", "pulls", "bytes_pushed", "bytes_pulled",
                    "bucket_flushes", "bucket_keys", "wait_calls",
                    "wait_ms_total", "bucket_fill_ratio", "avg_wait_ms"):
            assert key in stats
        assert stats["pushes"] == 1 and stats["pulls"] == 1
        # ...and is backed by the registry the Prometheus render reads
        text = telemetry.render_prometheus()
        assert "mxtpu_comm_pushes 1" in text
        assert "mxtpu_comm_queue_depth" in text  # live gauge
    finally:
        kv.close()
    # dead collector drops out of the global render
    import gc

    del kv
    gc.collect()
    assert "mxtpu_comm_pushes 1" not in telemetry.render_prometheus()


def test_serving_metrics_registry_backed():
    from mxnet_tpu.serving.metrics import ServingMetrics

    telemetry.enable(trace=False)
    m = ServingMetrics()
    m.on_submit(3)
    m.on_batch(bucket=4, occupancy=3)
    m.on_complete(1.5)
    text = m.render_text()
    assert "# TYPE mxtpu_serving_requests_total counter" in text
    assert "mxtpu_serving_requests_total 1" in text
    assert 'mxtpu_serving_batch_size{bucket="4"} 1' in text
    assert "mxtpu_serving_padded_items_total 1" in text
    # surfaced through the shared exposition as a collector
    assert "mxtpu_serving_requests_total 1" in telemetry.render_prometheus()
    assert m.snapshot()["requests_completed"] == 1


def test_fault_injection_counter():
    from mxnet_tpu import faults

    telemetry.enable(trace=False)
    plan = faults.FaultPlan("demo.op:delay=1@1ms", seed=3)
    plan.fire("demo.op")
    lc = telemetry.labeled_counter("mxtpu_faults_injected_total", "kind")
    assert lc.get("delay") == 1
    assert any(e["kind"] == "fault_injected" for e in telemetry.events())


def test_prefetch_iter_instrumented():
    telemetry.enable(trace=False)
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    base = mx.io.NDArrayIter(data, batch_size=5)
    it = mx.io.PrefetchingIter(base)
    n = sum(1 for _ in it)
    assert n == 4
    text = telemetry.render_prometheus()
    assert "mxtpu_prefetch_batches_total 4" in text


# ---------------------------------------------------------------------------
# tools/telemetry_dump.py
# ---------------------------------------------------------------------------
def test_telemetry_dump_tool_smoke(tmp_path):
    telemetry.enable(trace=True)
    with telemetry.span("tool.span", "test"):
        pass
    trace_a = tmp_path / "a.json"
    telemetry.dump_trace(str(trace_a))
    events = tmp_path / "events.jsonl"
    events.write_text(json.dumps({"ts": 1.0, "kind": "step", "n": 1}) + "\n" +
                      json.dumps({"ts": 2.5, "kind": "compile"}) + "\n")
    tool = os.path.join(REPO, "tools", "telemetry_dump.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    r = subprocess.run([sys.executable, tool, "events", str(events),
                        "--tail", "5"], capture_output=True, text=True,
                       env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "step" in r.stdout and "2 event(s)" in r.stdout

    merged = tmp_path / "merged.json"
    r = subprocess.run([sys.executable, tool, "trace", str(trace_a),
                        str(trace_a), "-o", str(merged)],
                       capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    payload = json.loads(merged.read_text())
    telemetry.validate_trace(payload)
    assert any(e.get("name") == "tool.span" for e in payload["traceEvents"])


# ---------------------------------------------------------------------------
# Speedometer data-wait satellite
# ---------------------------------------------------------------------------
def test_speedometer_reports_data_wait():
    telemetry.enable(trace=False)
    spd = mx.callback.Speedometer(batch_size=10, frequent=2)
    _fit_small(speedometer=spd)
    assert spd.last_speed is not None and spd.last_speed > 0
    assert spd.last_data_wait_ms is not None
    assert spd.last_data_wait_ms >= 0.0


def test_speedometer_without_telemetry():
    spd = mx.callback.Speedometer(batch_size=10, frequent=2)
    _fit_small(speedometer=spd)
    assert spd.last_speed is not None
    assert spd.last_data_wait_ms is None


def test_disabled_overhead_distributed_two_workers():
    """Satellite of the cluster-observability PR: the <2% disabled-cost
    guard extended to a 2-worker kvstore exchange.  Off, the dist RPC
    path adds exactly two gate reads per RPC (client _rpc + server
    _dispatch_timed) and keeps the plain 4-element wire envelope."""
    from mxnet_tpu import kvstore_server as kvs

    assert not telemetry.enabled()
    srv = kvs.start_server(num_workers=2)
    clients = []
    try:
        host, port = srv.addr
        clients = [kvs.ServerClient(host, port) for _ in range(2)]
        clients[0].init("w", np.zeros(8, np.float32))
        # structural check: no trace ctx rides the wire while off
        ent = clients[0]._submit(("membership",))
        ent["event"].wait()
        assert len(ent["env"]) == 4

        # measured per-RPC time across both workers, steady state
        for c in clients:
            c.push("w", np.ones(8, np.float32))
            c.pull("w")
        n = 50
        t0 = time.perf_counter()
        for _ in range(n):
            for c in clients:
                c.push("w", np.ones(8, np.float32))
                c.pull("w")
        per_rpc_s = (time.perf_counter() - t0) / (n * 4)

        m = 200_000
        per_gate_s = timeit.timeit(telemetry.enabled, number=m) / m
        gates_per_rpc = 2  # client-side _rpc + server-side dispatch
        assert per_gate_s * gates_per_rpc < 0.02 * per_rpc_s, \
            "telemetry-off gate cost %.3fus x %d vs rpc %.1fus" % (
                per_gate_s * 1e6, gates_per_rpc, per_rpc_s * 1e6)
    finally:
        for c in clients:
            c.close()
        srv.stop()

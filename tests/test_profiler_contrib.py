"""Profiler wiring + mx.contrib namespace tests.

Reference behaviors covered:
  * profiler events emitted from the real execution path so
    ``dump_profile`` after a fit is non-empty (src/engine/profiler.h:88-109
    stamps every executed op; here the spans are step-level)
  * ``mx.contrib.sym.MultiBoxPrior`` spelling works
    (python/mxnet/contrib/symbol.py)
  * TensorBoard LogMetricsCallback (python/mxnet/contrib/tensorboard.py:8)
"""
import glob
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _fit_small(tmp_path, batch_end_callback=None, num_epoch=1):
    np.random.seed(0)
    X = np.random.randn(50, 10).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=batch_end_callback)
    return mod


def test_profiler_records_fit_steps(tmp_path):
    fname = str(tmp_path / "profile.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    try:
        _fit_small(tmp_path)  # 5 batches x 1 epoch
    finally:
        mx.profiler.profiler_set_state("stop")
    out = mx.profiler.dump_profile()
    with open(out) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    steps = [e for e in events if e["name"] == "Module.fit:step"]
    execs = [e for e in events if e["name"].startswith("Executor.")]
    epochs = [e for e in events if e["name"].startswith("Module.fit:epoch")]
    assert len(steps) >= 5, "expected >=1 event per fit step, got %d" % len(steps)
    assert len(execs) >= 5, "executor spans missing from the profile"
    assert len(epochs) == 1
    # chrome trace shape: complete events with ts+dur
    assert all(e["ph"] == "X" and "dur" in e for e in events)


def test_profiler_off_means_no_events(tmp_path):
    fname = str(tmp_path / "p2.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    # run/stop cycle clears any events kept from a previous profile session
    mx.profiler.profiler_set_state("run")
    mx.profiler.profiler_set_state("stop")
    _fit_small(tmp_path)  # profiler stopped: must record nothing
    out = mx.profiler.dump_profile()
    with open(out) as f:
        assert json.load(f)["traceEvents"] == []


def test_contrib_namespace_spellings():
    # the exact spellings reference scripts use
    assert callable(mx.contrib.sym.MultiBoxPrior)
    assert callable(mx.contrib.sym.MultiBoxTarget)
    assert callable(mx.contrib.sym.MultiBoxDetection)
    assert callable(mx.contrib.nd.fft)
    data = mx.sym.Variable("data")
    anchors = mx.contrib.sym.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,))
    _, out_shapes, _ = anchors.infer_shape(data=(1, 3, 8, 8))
    assert out_shapes[0] == (1, 64, 4)
    # imperative contrib op
    x = mx.nd.array(np.random.randn(2, 8).astype(np.float32))
    out = mx.contrib.nd.fft(x)
    assert out.shape == (2, 16)


def test_tensorboard_log_metrics_callback(tmp_path):
    logdir = str(tmp_path / "tb")
    cb = mx.contrib.tensorboard.LogMetricsCallback(logdir, prefix="train")
    _fit_small(tmp_path, batch_end_callback=cb)
    assert cb.step >= 5
    wrote_tb = bool(glob.glob(os.path.join(logdir, "events.out.tfevents.*")))
    wrote_jsonl = os.path.exists(os.path.join(logdir, "scalars.jsonl"))
    assert wrote_tb or wrote_jsonl

"""Standalone kvstore server process for chaos tests — the kill -9 target.

Usage: chaos_kv_server.py HOST PORT SNAPSHOT_PATH

Serves until a cooperative stop command (exit 0) or an external SIGKILL;
on restart with the same SNAPSHOT_PATH it restores the journaled state.
"""
import sys


def main():
    host, port, snap = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    from mxnet_tpu import kvstore_server as kvs

    srv = kvs.KVStoreServer(host, port, num_workers=1, sync_mode=False,
                            snapshot_path=snap, snapshot_interval=0)
    srv.serve_forever()


if __name__ == "__main__":
    main()

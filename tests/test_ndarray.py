"""NDArray imperative surface tests (reference: tests/python/unittest/test_ndarray.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, same


def test_creation_and_basic_props():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.size == 4
    assert a.ndim == 2
    assert a.dtype == np.float32
    assert same(a, np.array([[1, 2], [3, 4]], dtype=np.float32))


def test_zeros_ones_full_arange():
    assert same(nd.zeros((2, 3)), np.zeros((2, 3), np.float32))
    assert same(nd.ones((3,)), np.ones(3, np.float32))
    assert same(nd.arange(0, 10, 2), np.arange(0, 10, 2, dtype=np.float32))


def test_elementwise_arith():
    a_np = np.random.randn(3, 4).astype(np.float32)
    b_np = np.random.randn(3, 4).astype(np.float32)
    a, b = nd.array(a_np), nd.array(b_np)
    assert_almost_equal(a + b, a_np + b_np)
    assert_almost_equal(a - b, a_np - b_np)
    assert_almost_equal(a * b, a_np * b_np)
    assert_almost_equal(a / b, a_np / b_np, rtol=1e-4, atol=1e-5)
    assert_almost_equal(a + 2.5, a_np + 2.5)
    assert_almost_equal(2.5 - a, 2.5 - a_np)
    assert_almost_equal(-a, -a_np)
    assert_almost_equal(abs(a), np.abs(a_np))


def test_inplace_ops():
    a_np = np.ones((2, 2), np.float32)
    a = nd.array(a_np)
    a += 3
    assert_almost_equal(a, a_np + 3)
    a *= 2
    assert_almost_equal(a, (a_np + 3) * 2)


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    assert same(a == b, np.array([0, 1, 0], np.float32))
    assert same(a > b, np.array([0, 0, 1], np.float32))
    assert same(a <= b, np.array([1, 1, 0], np.float32))


def test_indexing_and_setitem():
    a_np = np.arange(12, dtype=np.float32).reshape(3, 4)
    a = nd.array(a_np)
    assert_almost_equal(a[1], a_np[1])
    assert_almost_equal(a[1:3], a_np[1:3])
    a[0] = 42.0
    a_np[0] = 42.0
    assert_almost_equal(a, a_np)


def test_slice_returns_copy_documented_deviation():
    # Deviation from reference ndarray.h:286-352 (zero-copy Slice): our slices
    # are copies; writes to a slice do NOT propagate to the parent.
    a = nd.array(np.arange(6, dtype=np.float32))
    s = a.slice(0, 3)
    s[:] = 99.0
    assert a.asnumpy()[0] == 0.0


def test_reshape_transpose():
    a_np = np.arange(6, dtype=np.float32).reshape(2, 3)
    a = nd.array(a_np)
    assert_almost_equal(a.reshape((3, 2)), a_np.reshape(3, 2))
    assert_almost_equal(a.T, a_np.T)


def test_astype_copyto():
    a = nd.array([1.5, 2.5])
    b = a.astype(np.int32)
    assert b.dtype == np.int32
    c = nd.zeros((2,))
    a.copyto(c)
    assert_almost_equal(c, a)


def test_dot():
    a_np = np.random.randn(3, 4).astype(np.float32)
    b_np = np.random.randn(4, 5).astype(np.float32)
    out = nd.dot(nd.array(a_np), nd.array(b_np))
    assert_almost_equal(out, a_np @ b_np, rtol=1e-4, atol=1e-4)


def test_broadcast_ops():
    a_np = np.random.randn(3, 1).astype(np.float32)
    b_np = np.random.randn(1, 4).astype(np.float32)
    out = nd.broadcast_add(nd.array(a_np), nd.array(b_np))
    assert_almost_equal(out, a_np + b_np)


def test_reduce_ops():
    a_np = np.random.randn(2, 3, 4).astype(np.float32)
    a = nd.array(a_np)
    assert_almost_equal(nd.sum(a, axis=1), a_np.sum(axis=1), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.max(a, axis=(0, 2)), a_np.max(axis=(0, 2)))
    assert_almost_equal(nd.mean(a), a_np.mean(), rtol=1e-5, atol=1e-6)


def test_save_load_roundtrip():
    arrays = {"w": nd.array(np.random.randn(3, 3).astype(np.float32)),
              "b": nd.array(np.array([1, 2, 3], dtype=np.int32))}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "test.params")
        nd.save(path, arrays)
        loaded = nd.load(path)
    assert set(loaded) == {"w", "b"}
    assert loaded["b"].dtype == np.int32  # dtype preserved (ADVICE fix)
    assert_almost_equal(loaded["w"], arrays["w"])
    assert_almost_equal(loaded["b"], arrays["b"])


def test_save_load_list():
    arrays = [nd.array([1.0, 2.0]), nd.array([[3.0]])]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "list.params")
        nd.save(path, arrays)
        loaded = nd.load(path)
    assert isinstance(loaded, list) and len(loaded) == 2
    assert_almost_equal(loaded[0], arrays[0])


def test_waitall():
    a = nd.array([1.0]) + 1
    nd.waitall()
    assert a.asnumpy()[0] == 2.0


def test_concat_stack():
    a_np = np.random.randn(2, 3).astype(np.float32)
    b_np = np.random.randn(2, 3).astype(np.float32)
    out = nd.concat(nd.array(a_np), nd.array(b_np), dim=0)
    assert_almost_equal(out, np.concatenate([a_np, b_np], axis=0))


def test_onehot_encode():
    idx = nd.array([0.0, 2.0])
    out = nd.one_hot(idx, depth=3)
    assert_almost_equal(out, np.eye(3, dtype=np.float32)[[0, 2]])


def test_broadcast_to_method():
    a = mx.nd.array(np.arange(3).reshape(1, 3))
    b = a.broadcast_to((4, 3))
    assert b.shape == (4, 3)
    np.testing.assert_array_equal(b.asnumpy(), np.tile(np.arange(3), (4, 1)))

    with pytest.raises(ValueError, match="broadcast"):
        a.broadcast_to((4, 5))
    with pytest.raises(ValueError, match="broadcast"):
        a.broadcast_to((3,))


def test_broadcast_to_rank_extension_and_zero():
    # reference semantics: shorter shapes left-pad with 1s; 0 keeps dim
    a = mx.nd.array(np.arange(3))
    b = a.broadcast_to((4, 3))
    assert b.shape == (4, 3)
    c = mx.nd.array(np.arange(3).reshape(1, 3)).broadcast_to((5, 0))
    assert c.shape == (5, 3)

"""WarpCTC plugin-op parity tests (reference
plugin/warpctc/warpctc-inl.h: softmax forward, CTC gradient backward,
blank=0, labels zero-stripped)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _ctc_loss_np(logp, labels):
    """Dense CTC forward (log domain) for a single sample — numpy oracle.
    logp: (T, P) log-probs; labels: list of ints (no blanks)."""
    ext = [0]
    for l in labels:
        ext += [l, 0]
    S = len(ext)
    T = logp.shape[0]
    NEG = -1e30
    alpha = np.full((T, S), NEG)
    alpha[0, 0] = logp[0, ext[0]]
    if S > 1:
        alpha[0, 1] = logp[0, ext[1]]
    for t in range(1, T):
        for s in range(S):
            cands = [alpha[t - 1, s]]
            if s >= 1:
                cands.append(alpha[t - 1, s - 1])
            if s >= 2 and ext[s] != 0 and ext[s] != ext[s - 2]:
                cands.append(alpha[t - 1, s - 2])
            m = max(cands)
            if m <= NEG / 2:
                continue
            alpha[t, s] = m + np.log(sum(np.exp(c - m) for c in cands)) \
                + logp[t, ext[s]]
    tail = [alpha[T - 1, S - 1]]
    if S > 1:
        tail.append(alpha[T - 1, S - 2])
    m = max(tail)
    return -(m + np.log(sum(np.exp(c - m) for c in tail)))


def test_warpctc_forward_is_softmax():
    T, N, P, L = 5, 2, 4, 3
    rng = np.random.RandomState(0)
    data = rng.randn(T * N, P).astype(np.float32)
    label = np.array([[1, 2, 0], [3, 0, 0]], np.float32)
    out = mx.nd.WarpCTC(mx.nd.array(data), mx.nd.array(label),
                        label_length=L, input_length=T)
    e = np.exp(data - data.max(axis=-1, keepdims=True))
    np.testing.assert_allclose(out.asnumpy(), e / e.sum(-1, keepdims=True),
                               rtol=1e-5)


def test_warpctc_gradient_matches_dense_oracle():
    """Symbolic backward == finite differences of the numpy CTC loss."""
    T, N, P, L = 6, 2, 5, 3
    rng = np.random.RandomState(1)
    data = rng.randn(T * N, P).astype(np.float32) * 0.5
    labels = [[2, 3, 0], [1, 0, 0]]  # zero-padded, blank-stripped by the op

    sym = mx.sym.WarpCTC(data=mx.sym.Variable("data"),
                         label=mx.sym.Variable("label"),
                         label_length=L, input_length=T)
    ex = sym.bind(mx.cpu(),
                  {"data": mx.nd.array(data),
                   "label": mx.nd.array(np.array(labels, np.float32))},
                  args_grad={"data": mx.nd.zeros((T * N, P))})
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones((T * N, P)))  # head grad must be ignored
    got = ex.grad_dict["data"].asnumpy()

    def total_loss(flat):
        x = flat.reshape(T, N, P)
        logp = x - x.max(-1, keepdims=True)
        logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
        return sum(_ctc_loss_np(logp[:, n],
                                [v for v in labels[n] if v != 0])
                   for n in range(N))

    flat = data.reshape(-1).astype(np.float64)
    eps = 1e-4
    num = np.zeros_like(flat)
    for i in range(flat.size):
        up = flat.copy(); up[i] += eps
        dn = flat.copy(); dn[i] -= eps
        num[i] = (total_loss(up) - total_loss(dn)) / (2 * eps)
    np.testing.assert_allclose(got.reshape(-1), num, rtol=1e-2, atol=1e-3)


def test_warpctc_training_drives_loss_down():
    """A linear model under WarpCTC learns a fixed target sequence."""
    T, N, P, L = 8, 4, 4, 2
    rng = np.random.RandomState(3)
    data = mx.nd.array(rng.randn(T * N, P).astype(np.float32) * 0.1)
    label = mx.nd.array(np.tile([1, 2], (N, 1)).astype(np.float32))
    grad = mx.nd.zeros((T * N, P))
    sym = mx.sym.WarpCTC(data=mx.sym.Variable("data"),
                         label=mx.sym.Variable("label"),
                         label_length=L, input_length=T)
    ex = sym.bind(mx.cpu(), {"data": data, "label": label},
                  args_grad={"data": grad})

    from mxnet_tpu.ops.ctc import _ctc_losses
    import jax.numpy as jnp

    def loss_now():
        return float(np.sum(np.asarray(_ctc_losses(
            jnp.asarray(data.asnumpy()), jnp.asarray(label.asnumpy()),
            T, L))))

    before = loss_now()
    for _ in range(30):
        ex.forward(is_train=True)
        ex.backward(mx.nd.ones((T * N, P)))
        data[:] = data - 0.5 * grad
    after = loss_now()
    assert after < before * 0.5, (before, after)


def test_lstm_ocr_example_learns():
    """The warpctc example end-to-end (reference example/warpctc/
    lstm_ocr.py): LSTM + WarpCTC on a generated frame-stream task;
    greedy-decode sequence accuracy far above chance."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."))
    from examples.warpctc import lstm_ocr

    acc = lstm_ocr.main(["--num-epochs", "5", "--num-samples", "192",
                         "--seq-len", "16", "--label-len", "3",
                         "--num-classes", "6", "--num-hidden", "48"])
    assert acc > 0.5, acc

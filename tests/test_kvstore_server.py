"""dist_async parameter-service tests (reference:
tests/nightly/dist_sync_kvstore.py run through tools/launch.py as local
processes, and the server loop in kvstore_dist_server.h:87-260)."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore_server as kvs
from mxnet_tpu.test_utils import assert_almost_equal


def test_wire_noncontiguous_array_falls_back_inband():
    """A non-contiguous ndarray (transposed/sliced view) cannot expose a
    flat pickle-5 buffer; the wire must fall back to in-band pickling
    instead of dying with BufferError mid-send."""
    import socket

    a, b = socket.socketpair()
    try:
        arr = np.arange(24, dtype=np.float32).reshape(4, 6).T  # not C-contig
        assert not arr.flags.c_contiguous
        kvs._send_msg(a, {"cmd": "push", "value": arr})
        msg = kvs._recv_msg(b)
        np.testing.assert_array_equal(msg["value"], arr)
        # contiguous arrays still take the zero-copy out-of-band path
        kvs._send_msg(a, np.ones(8, np.float32))
        np.testing.assert_array_equal(kvs._recv_msg(b), np.ones(8))
    finally:
        a.close()
        b.close()


def test_wire_version_mismatch_is_a_clear_error():
    import socket

    a, b = socket.socketpair()
    try:
        a.sendall(bytes([kvs._WIRE_VERSION + 1]) + b"\x00" * kvs._HDR.size)
        with pytest.raises(ConnectionError, match="wire version mismatch"):
            kvs._recv_msg(b)
    finally:
        a.close()
        b.close()


def test_server_async_accumulate():
    """No updater installed: pushes accumulate into the store."""
    srv = kvs.start_server(num_workers=2)
    try:
        host, port = srv.addr
        c1 = kvs.ServerClient(host, port)
        c2 = kvs.ServerClient(host, port)
        c1.init(3, np.zeros((2, 2), np.float32))
        c1.push(3, np.full((2, 2), 1.0, np.float32))
        c2.push(3, np.full((2, 2), 2.0, np.float32))
        out = c1.pull(3)
        assert_almost_equal(out, np.full((2, 2), 3.0, np.float32))
    finally:
        srv.stop()


def test_server_async_updater_applied_per_push():
    """With an SGD updater: every push updates immediately (async PS
    semantics, kvstore_dist_server.h:198-206)."""
    srv = kvs.start_server(num_workers=1)
    try:
        host, port = srv.addr
        c = kvs.ServerClient(host, port)
        c.init("w", np.ones((4,), np.float32))
        c.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        c.push("w", np.ones((4,), np.float32))  # w -= 0.5 * 1
        out1 = c.pull("w")
        c.push("w", np.ones((4,), np.float32))
        out2 = c.pull("w")
        assert out1.mean() < 1.0
        assert out2.mean() < out1.mean()
    finally:
        srv.stop()


def test_server_sync_mode_merges_all_workers():
    """sync_mode: update fires only after num_workers pushes merge
    (kvstore_dist_server.h:164-179)."""
    srv = kvs.start_server(num_workers=2, sync_mode=True)
    try:
        host, port = srv.addr
        c1 = kvs.ServerClient(host, port)
        c2 = kvs.ServerClient(host, port)
        c1.init(0, np.zeros((3,), np.float32))
        c1.push(0, np.full((3,), 1.0, np.float32), rank=0)
        # only one of two workers pushed: store unchanged
        assert_almost_equal(c1.pull(0), np.zeros((3,), np.float32))
        c2.push(0, np.full((3,), 2.0, np.float32), rank=1)
        assert_almost_equal(c1.pull(0), np.full((3,), 3.0, np.float32))
    finally:
        srv.stop()


def test_server_sync_mode_per_worker_rounds():
    """A fast worker's second push must open a new round, not complete the
    current one (reference merges one push per worker per round)."""
    srv = kvs.start_server(num_workers=2, sync_mode=True)
    try:
        host, port = srv.addr
        c = kvs.ServerClient(host, port)
        c.init(0, np.zeros((3,), np.float32))
        c.push(0, np.full((3,), 1.0, np.float32), rank=0)  # round 1
        c.push(0, np.full((3,), 10.0, np.float32), rank=0)  # round 2
        # still waiting on worker 1 for round 1
        assert_almost_equal(c.pull(0), np.zeros((3,), np.float32))
        c.push(0, np.full((3,), 2.0, np.float32), rank=1)  # completes round 1
        assert_almost_equal(c.pull(0), np.full((3,), 3.0, np.float32))
        c.push(0, np.full((3,), 20.0, np.float32), rank=1)  # completes round 2
        assert_almost_equal(c.pull(0), np.full((3,), 33.0, np.float32))
    finally:
        srv.stop()


def test_server_error_reply_not_connection_drop():
    """A failing command must return an err reply, not kill the handler."""
    import pytest as _pytest

    srv = kvs.start_server(num_workers=1)
    try:
        host, port = srv.addr
        c = kvs.ServerClient(host, port)
        with _pytest.raises(Exception, match="kvstore server error"):
            c._rpc("set_optimizer", b"not-a-pickle")
        # connection still alive and serving
        c.init(1, np.ones((2,), np.float32))
        assert_almost_equal(c.pull(1), np.ones((2,), np.float32))
    finally:
        srv.stop()


def test_server_barrier():
    srv = kvs.start_server(num_workers=2)
    try:
        host, port = srv.addr
        order = []

        def worker(i):
            c = kvs.ServerClient(host, port)
            if i == 1:
                time.sleep(0.3)
            c.barrier(rank=i)  # arrivals are rank-keyed
            order.append(i)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert len(order) == 2
        assert time.time() - t0 >= 0.25  # fast worker waited for slow one
    finally:
        srv.stop()


def test_dist_async_kvstore_facade():
    """mx.kvstore.create('dist_async') without env: in-process service;
    Module-style init/push/pull cycle works."""
    kv = mx.kvstore.create("dist_async")
    assert kv.type == "dist_async"
    assert kv.rank == 0 and kv.num_workers == 1
    kv.init(9, mx.nd.ones((2, 3)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push(9, [mx.nd.ones((2, 3))])
    out = mx.nd.zeros((2, 3))
    kv.pull(9, out=out)
    assert out.asnumpy().mean() < 1.0
    kv._send_command_to_servers("stop", "")


def test_server_role_bootstrap_subprocess():
    """Reference launch pattern: a process with DMLC_ROLE=server serves on
    import; two worker processes push known values; sum must match
    (tests/nightly/dist_sync_kvstore.py:30-44 analytics)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu", DMLC_ROLE="server",
               DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=str(port),
               DMLC_NUM_WORKER="2")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    server = subprocess.Popen([sys.executable, "-c", "import mxnet_tpu"],
                              env=env, cwd=repo)
    try:
        # wait for the server socket
        for _ in range(100):
            try:
                c = kvs.ServerClient("127.0.0.1", port)
                break
            except OSError:
                time.sleep(0.1)
        else:
            pytest.fail("server did not come up")
        c.init(7, np.zeros((4,), np.float32))

        def worker_main(rank):
            env_w = dict(env, DMLC_ROLE="worker", DMLC_WORKER_ID=str(rank))
            code = (
                "import mxnet_tpu as mx, numpy as np\n"
                "kv = mx.kvstore.create('dist_async')\n"
                "kv.push(7, [mx.nd.array(np.full((4,), %d, np.float32))])\n"
                "kv._barrier()\n" % (rank + 1))
            return subprocess.Popen([sys.executable, "-c", code], env=env_w,
                                    cwd=repo)
        workers = [worker_main(r) for r in range(2)]
        for w in workers:
            assert w.wait(timeout=120) == 0
        out = c.pull(7)
        assert_almost_equal(out, np.full((4,), 3.0, np.float32))
        c.stop_server()
        assert server.wait(timeout=30) == 0
    finally:
        if server.poll() is None:
            server.kill()


def test_dist_async_bigarray_range_split(monkeypatch):
    """Arrays >= MXNET_KVSTORE_BIGARRAY_BOUND elements are range-split
    across the server fleet (reference kvstore_dist.h:264-302): each
    server holds only its contiguous slice, and init/push/pull round-trip
    the full array; small keys stay whole on one crc32-assigned server."""
    s0 = kvs.start_server(num_workers=1)
    s1 = kvs.start_server(num_workers=1)
    try:
        host, p0 = s0.addr
        monkeypatch.setenv("DMLC_PS_ROOT_URI", host)
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(p0))
        monkeypatch.setenv("DMLC_SERVER_URIS",
                           "%s:%d,%s:%d" % (host, p0, host, s1.addr[1]))
        monkeypatch.setenv("DMLC_NUM_SERVER", "2")
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "100")

        kv = mx.kvstore.create("dist_async")
        try:
            big = np.arange(250, dtype=np.float32).reshape(5, 50)
            kv.init("big", mx.nd.array(big))
            # each server holds only its contiguous range
            assert s0.store["big"].size == 125
            assert s1.store["big"].size == 125
            assert_almost_equal(
                np.concatenate([s0.store["big"], s1.store["big"]]),
                big.reshape(-1))

            kv.push("big", mx.nd.array(np.ones((5, 50), np.float32)))
            out = mx.nd.zeros((5, 50))
            kv.pull("big", out=out)
            assert_almost_equal(out.asnumpy(), big + 1.0)

            # under the bound: whole array on exactly one server
            small = np.ones((4,), np.float32)
            kv.init("small", mx.nd.array(small))
            holders = [s for s in (s0, s1) if "small" in s.store]
            assert len(holders) == 1
            out_s = mx.nd.zeros((4,))
            kv.pull("small", out=out_s)
            assert_almost_equal(out_s.asnumpy(), small)

            # server-side optimizer applies per slice (elementwise update)
            kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
            kv.push("big", mx.nd.array(np.full((5, 50), 2.0, np.float32)))
            kv.pull("big", out=out)
            assert_almost_equal(out.asnumpy(), big + 1.0 - 0.5 * 2.0)
        finally:
            kv.close()
    finally:
        s0.stop()
        s1.stop()


def test_dist_async_recovery_worker_skips_barrier(monkeypatch):
    """Rejoin semantics (reference kvstore_dist.h:35-38 IsRecovery): a
    relaunched worker's init/set_optimizer must NOT wait at the startup
    barrier — its peers are mid-training and will never arrive — must not
    clobber the server's live weights, and must pull the current ones."""
    srv = kvs.start_server(num_workers=2)  # barrier needs 2: would hang
    try:
        host, port = srv.addr
        # the job passed startup (one full barrier generation) and
        # trained for a while before the worker died
        live = kvs.ServerClient(host, port)
        live2 = kvs.ServerClient(host, port)
        t0 = threading.Thread(target=lambda: live2.barrier(rank=0))
        t0.start()
        live.barrier(rank=1)
        t0.join(timeout=10)
        live.init("w", np.zeros((4,), np.float32))
        live.push("w", np.full((4,), 7.0, np.float32))

        monkeypatch.setenv("DMLC_PS_ROOT_URI", host)
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
        monkeypatch.setenv("DMLC_NUM_SERVER", "1")
        monkeypatch.setenv("DMLC_NUM_WORKER", "2")
        monkeypatch.setenv("DMLC_WORKER_ID", "1")
        monkeypatch.setenv("DMLC_IS_RECOVERY", "1")

        done = {}

        def rejoin():
            kv = mx.kvstore.create("dist_async")
            try:
                # re-init must return immediately (no barrier) and must
                # not reset the trained value (server init is setdefault)
                kv.init("w", mx.nd.zeros((4,)))
                kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
                out = mx.nd.zeros((4,))
                kv.pull("w", out=out)
                done["w"] = out.asnumpy()
            finally:
                kv.close()

        t = threading.Thread(target=rejoin)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive(), \
            "recovery worker blocked at the startup barrier"
        assert_almost_equal(done["w"], np.full((4,), 7.0, np.float32))
    finally:
        srv.stop()


def test_recovery_before_startup_joins_barrier():
    """The deadlock guard: a worker relaunched BEFORE the job's first
    barrier completed must JOIN the startup barrier (completing it for
    the waiting peers), not skip it — skipping would strand the peers
    until the 600s timeout."""
    srv = kvs.start_server(num_workers=2)
    try:
        host, port = srv.addr
        results = []

        def healthy():
            c = kvs.ServerClient(host, port)
            c.barrier(rank=0)  # waits for the second worker
            results.append("healthy")

        def recovered():
            time.sleep(0.3)
            c = kvs.ServerClient(host, port)
            # is_recovery, but no generation has completed: must join
            c.barrier(rank=1, is_recovery=True)
            results.append("recovered")

        ts = [threading.Thread(target=healthy),
              threading.Thread(target=recovered)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=15)
        assert sorted(results) == ["healthy", "recovered"], results
    finally:
        srv.stop()


def test_recovery_set_optimizer_keeps_live_updater():
    """A rejoining rank 0 re-ships its optimizer; the server must keep
    the installed updater (its momentum state is live mid-training)."""
    srv = kvs.start_server(num_workers=1)
    try:
        host, port = srv.addr
        c = kvs.ServerClient(host, port)
        c.init("w", np.ones((2,), np.float32))
        c.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        first = srv.updater
        # recovery re-ship: ignored while an updater is installed
        c.set_optimizer(mx.optimizer.SGD(learning_rate=0.1),
                        is_recovery=True)
        assert srv.updater is first
        # but a recovery send with NO updater installed (crash before
        # set_optimizer completed) does install
        srv.updater = None
        c.set_optimizer(mx.optimizer.SGD(learning_rate=0.1),
                        is_recovery=True)
        assert srv.updater is not None
    finally:
        srv.stop()


def test_recovery_joins_pending_barrier_mid_training():
    """Even past startup, a recovered worker must JOIN a barrier peers
    are already parked at (they count num_workers arrivals; skipping
    would wedge them to the 600s timeout) — and skip only when nobody
    is waiting."""
    srv = kvs.start_server(num_workers=2)
    try:
        host, port = srv.addr
        a = kvs.ServerClient(host, port)
        b = kvs.ServerClient(host, port)
        # pass startup: one full generation
        t = threading.Thread(target=lambda: a.barrier(rank=0))
        t.start()
        b.barrier(rank=1)
        t.join(timeout=10)

        # rank 0 parks at a new barrier; recovered rank 1 must release it
        released = []
        t = threading.Thread(
            target=lambda: (a.barrier(rank=0), released.append(True)))
        t.start()
        time.sleep(0.3)
        assert not released  # genuinely parked
        b.barrier(rank=1, is_recovery=True)  # pending -> joins
        t.join(timeout=10)
        assert released, "recovery join did not release the parked peer"

        # nobody waiting now: recovery barrier returns immediately
        t0 = time.time()
        b.barrier(rank=1, is_recovery=True)
        assert time.time() - t0 < 2.0
    finally:
        srv.stop()


def test_recovery_flag_expires_at_first_push(monkeypatch):
    """The recovery flag covers only bring-up: after the first PUSH (real
    training traffic), a later legitimate set_optimizer — the LR-drop-at-
    epoch-boundary pattern — must install on the server instead of being
    dropped as a recovery re-ship. Bring-up pulls must NOT expire it
    (Module interleaves init/pull per parameter)."""
    srv = kvs.start_server(num_workers=1)
    try:
        host, port = srv.addr
        monkeypatch.setenv("DMLC_PS_ROOT_URI", host)
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
        monkeypatch.setenv("DMLC_NUM_SERVER", "1")
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_IS_RECOVERY", "1")

        # pre-existing live state from before the crash
        boot = kvs.ServerClient(host, port)
        boot.init("w", np.ones((2,), np.float32))
        boot.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        live_updater = srv.updater

        kv = mx.kvstore.create("dist_async")
        try:
            assert kv._is_recovery
            kv.init("w", mx.nd.ones((2,)))
            out = mx.nd.zeros((2,))
            kv.pull("w", out=out)  # bring-up pull: flag survives
            assert kv._is_recovery
            kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
            assert srv.updater is live_updater  # recovery re-ship dropped

            kv.push("w", mx.nd.ones((2,)))  # training traffic
            assert not kv._is_recovery
            kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.01))
            assert srv.updater is not live_updater  # LR drop installed
        finally:
            kv.close()
    finally:
        srv.stop()


def test_client_close_idempotent_and_context_manager():
    """close() is safe to call any number of times, __exit__ closes, the
    heartbeat thread is joined on close, and a closed client refuses
    further RPCs instead of hanging on a dead socket."""
    srv = kvs.start_server(num_workers=1)
    try:
        host, port = srv.addr
        with kvs.ServerClient(host, port) as c:
            c.init(1, np.ones(2, np.float32))
            c.start_heartbeat(0, interval=0.05)
            hb = c._hb_thread
            assert hb is not None and hb.is_alive()
        # the context exit ran close(): heartbeat joined, socket dropped
        assert c._closed
        assert c._hb_thread is None and not hb.is_alive()
        assert c._sock is None
        c.close()  # second (and third) close: no-op, no exception
        c.close()
        with pytest.raises(ConnectionError, match="closed"):
            c.pull(1)
    finally:
        srv.stop()


def test_client_reconnects_through_server_socket_loss():
    """Dropping the established TCP connection under the client must be
    invisible to the caller: the next RPC reconnects and replays."""
    srv = kvs.start_server(num_workers=1)
    try:
        host, port = srv.addr
        with kvs.ServerClient(host, port) as c:
            c.init(2, np.full(3, 4.0, np.float32))
            # sever the transport out from under the client
            c._sock.shutdown(__import__("socket").SHUT_RDWR)
            c._sock.close()
            out = c.pull(2)  # reconnect + replay, not an exception
            np.testing.assert_array_equal(out, np.full(3, 4.0, np.float32))
    finally:
        srv.stop()


def test_membership_rpcs_counted_and_timed_per_command():
    """Telemetry labels every server RPC — including the elastic
    membership commands — with a per-command counter sample and a
    per-command latency histogram."""
    from mxnet_tpu import telemetry

    telemetry._reset_for_tests()
    telemetry.enable(trace=False)
    srv = kvs.start_server(num_workers=2)
    try:
        with kvs.ServerClient(*srv.addr) as c:
            c.join(0)
            c.join(1)
            c.membership()
            c.evict(1)
            c.leave(0)
            c.init("k", np.zeros(2, np.float32))
            c.multi([("push", "k", np.ones(2, np.float32), 0),
                     ("pull", "k")])
        text = telemetry.render_prometheus()
        for cmd, n in (("join", 2), ("membership", 1), ("evict", 1),
                       ("leave", 1), ("init", 1), ("multi", 1)):
            assert 'mxtpu_kvsrv_rpc_total{cmd="%s"} %d' % (cmd, n) in text
            assert "mxtpu_kvsrv_rpc_%s_ms_count %d" % (cmd, n) in text
        # the fused bucket's INNER commands are counted too (the bucket
        # itself is one timed RPC)
        assert 'mxtpu_kvsrv_rpc_total{cmd="push"} 1' in text
        assert 'mxtpu_kvsrv_rpc_total{cmd="pull"} 1' in text
    finally:
        srv.stop()
        telemetry._reset_for_tests()

"""Notebook training-curve callbacks (reference
python/mxnet/notebook/callback.py capability: metric collection,
export, live curve — headless-friendly here)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.notebook.callback import LiveLearningCurve, MetricsLogger


def test_metrics_logger_collects_through_fit(tmp_path, capsys):
    rng = np.random.RandomState(0)
    X = rng.randn(64, 10).astype(np.float32)
    w = rng.randn(10, 2).astype(np.float32)
    y = (X @ w).argmax(axis=1).astype(np.float32)
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=2), name="softmax")
    logger = MetricsLogger(frequent=1)
    live = LiveLearningCurve(metric_name="accuracy", frequent=1)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    val = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net)
    mod.fit(it, eval_data=val, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            batch_end_callback=[logger.train_cb, live.train_cb],
            eval_end_callback=logger.eval_cb)
    accs = logger.values("accuracy")
    assert len(accs) >= 3
    assert accs[-1] > accs[0] or accs[-1] > 0.9  # learning visible
    assert logger.values("accuracy", "eval")  # eval phase collected too
    # sparkline renders one glyph per point (capped at width)
    line = logger.sparkline("accuracy", width=10)
    assert 0 < len(line) <= 10
    assert "accuracy" in capsys.readouterr().out  # live curve printed
    # csv export round-trips
    path = tmp_path / "curves.csv"
    logger.to_csv(str(path))
    rows = path.read_text().strip().splitlines()
    assert rows[0].startswith("phase,metric")
    assert any(r.startswith("train,accuracy") for r in rows[1:])
    assert any(r.startswith("eval,accuracy") for r in rows[1:])
    # a nan sample (metric before any update) must not break rendering
    logger._append(logger.train, "accuracy", float("nan"), 99, 0)
    assert len(logger.sparkline("accuracy")) > 0

"""Transformer LM model family: builder shapes, Module training through
the Pallas flash-attention op, LayerNorm/gelu op parity."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_layer_norm_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6, 8).astype(np.float32)
    g = rng.rand(8).astype(np.float32) + 0.5
    b = rng.randn(8).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b),
                          eps=1e-5)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mean) / np.sqrt(var + 1e-5) * g + b
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_layer_norm_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    net = mx.sym.LayerNorm(mx.sym.Variable("data"), mx.sym.Variable("gamma"),
                           mx.sym.Variable("beta"))
    rng = np.random.RandomState(1)
    check_numeric_gradient(
        net, {"data": rng.randn(3, 7).astype(np.float32),
              "gamma": rng.rand(7).astype(np.float32) + 0.5,
              "beta": rng.randn(7).astype(np.float32)},
        numeric_eps=1e-3, rtol=1e-2, atol=1e-2)


def test_layer_norm_output_mean_var():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 5).astype(np.float32)
    net = mx.sym.LayerNorm(mx.sym.Variable("data"), mx.sym.Variable("gamma"),
                           mx.sym.Variable("beta"), output_mean_var=True)
    assert len(net.list_outputs()) == 3
    ex = net.bind(mx.cpu(), {"data": mx.nd.array(x),
                             "gamma": mx.nd.ones((5,)),
                             "beta": mx.nd.zeros((5,))})
    ex.forward(is_train=False)
    out, mean, std = (o.asnumpy() for o in ex.outputs)
    np.testing.assert_allclose(mean, x.mean(-1), rtol=1e-5, atol=1e-6)
    # upstream's third output is the standard deviation (out, mean, std)
    np.testing.assert_allclose(std, np.sqrt(x.var(-1) + 1e-5),
                               rtol=1e-4, atol=1e-5)


def test_gelu_erf_ops():
    x = np.linspace(-3, 3, 13).astype(np.float32)
    g = mx.nd.gelu(mx.nd.array(x)).asnumpy()
    from scipy.special import erf as sp_erf
    ref = 0.5 * x * (1 + sp_erf(x / np.sqrt(2)))
    np.testing.assert_allclose(g, ref, rtol=1e-3, atol=1e-4)
    e = mx.nd.erf(mx.nd.array(x)).asnumpy()
    np.testing.assert_allclose(e, sp_erf(x), rtol=1e-5, atol=1e-6)


def test_transformer_shapes():
    net = mx.models.get_transformer_lm(vocab_size=100, num_layers=2,
                                       num_heads=4, hidden=64, seq_len=16)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(8, 16),
                                                softmax_label=(8, 16))
    assert out_shapes[0] == (8 * 16, 100)
    names = net.list_arguments()
    assert "pos_embed_weight" in names and "tok_embed_weight" in names


def test_transformer_lm_learns_next_token():
    """End-to-end: Module.fit on a deterministic next-token task reaches
    ~perfect accuracy — exercises Embedding/LayerNorm/gelu/flash-attention
    fwd+bwd through the fused step."""
    V, S, B = 50, 32, 4
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=2,
                                       num_heads=4, hidden=64, seq_len=S)
    rng = np.random.RandomState(0)
    X = rng.randint(0, V, size=(64, S)).astype(np.float32)
    Y = (X + 1) % V
    it = mx.io.NDArrayIter(X, Y, batch_size=B, label_name="softmax_label")
    mod = mx.mod.Module(net, label_names=("softmax_label",))
    mod.fit(it, num_epoch=6, optimizer="adam",
            optimizer_params={"learning_rate": 1e-2})
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        lab = batch.label[0].asnumpy().reshape(-1)
        correct += (out.argmax(-1) == lab).sum()
        total += lab.size
    assert correct / total > 0.9, correct / total


def test_splash_attention_op_matches_oracle():
    """_contrib_SplashAttention (upstream splash kernel behind the op
    registry, interpret mode on CPU): forward matches the dense oracle
    and gradients flow through splash's own custom vjp in the executor."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel.ring import local_attention

    rng = np.random.RandomState(1)
    b, s, h, d = 1, 128, 2, 64
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) * 0.3
               for _ in range(3))
    o = mx.nd._contrib_SplashAttention(mx.nd.array(q), mx.nd.array(k),
                                       mx.nd.array(v))
    ref = np.asarray(local_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=True))
    np.testing.assert_allclose(o.asnumpy(), ref, rtol=1e-4, atol=1e-5)

    net = mx.sym._contrib_SplashAttention(
        mx.sym.Variable("q"), mx.sym.Variable("k"), mx.sym.Variable("v"))
    ex = net.bind(mx.cpu(),
                  {"q": mx.nd.array(q), "k": mx.nd.array(k),
                   "v": mx.nd.array(v)},
                  args_grad={n: mx.nd.zeros((b, s, h, d))
                             for n in ("q", "k", "v")})
    ex.forward(is_train=True)
    head = rng.randn(b, s, h, d).astype(np.float32)
    ex.backward(out_grads=[mx.nd.array(head)])
    gq = jax.grad(lambda q: jnp.sum(local_attention(
        q, jnp.asarray(k), jnp.asarray(v), causal=True)
        * jnp.asarray(head)))(jnp.asarray(q))
    np.testing.assert_allclose(ex.grad_dict["q"].asnumpy(), np.asarray(gq),
                               rtol=1e-3, atol=1e-4)


def test_transformer_lm_splash_impl_learns():
    """The LM family's attn_impl='splash' A/B path trains through the
    Module fused step (tiny synthetic next-token task)."""
    rng = np.random.RandomState(0)
    vocab, s, b = 16, 128, 4  # splash needs seq multiples of 128
    X = rng.randint(0, vocab, size=(8 * b, s)).astype(np.float32)
    Y = (X + 1) % vocab
    it = mx.io.NDArrayIter(X, Y, batch_size=b, label_name="softmax_label")
    net = mx.models.get_transformer_lm(
        vocab_size=vocab, num_layers=1, num_heads=2, hidden=32,
        seq_len=s, attn_impl="splash")
    mod = mx.mod.Module(net, label_names=("softmax_label",))
    metric = mx.metric.Perplexity(ignore_label=None)
    mod.fit(it, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3}, eval_metric=metric)
    assert metric.get()[1] < 8.0, metric.get()  # vocab/2 baseline ~16

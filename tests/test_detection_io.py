"""Detection data pipeline tests: det label packing, box-aware augmenters,
ImageDetRecordIter consuming an im2rec-packed .rec, and SSD training on it
(reference: src/io/iter_image_det_recordio.cc:475-563,
src/io/image_det_aug_default.cc; nightly gate tests/nightly/test_all.sh).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as mximage
from mxnet_tpu import image_backend

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def test_det_label_roundtrip():
    objs = np.array([[1, 0.1, 0.2, 0.5, 0.6], [0, 0.3, 0.3, 0.9, 0.8]],
                    np.float32)
    flat = mximage._det_encode_label(objs)
    assert flat[0] == 2 and flat[1] == 5
    back = mximage._det_parse_label(flat)
    np.testing.assert_allclose(back, objs)


def test_det_flip_aug_transforms_boxes():
    img = np.zeros((8, 8, 3), np.float32)
    img[:, :4, 0] = 1.0  # left half red
    objs = np.array([[0, 0.0, 0.25, 0.5, 0.75]], np.float32)
    aug = mximage.DetHorizontalFlipAug(1.1)  # always flips
    out, lab = aug(img, objs)
    assert out[:, 4:, 0].all() and not out[:, :4, 0].any()
    np.testing.assert_allclose(lab[0], [0, 0.5, 0.25, 1.0, 0.75])
    out2, lab2 = aug(out, lab)
    np.testing.assert_allclose(out2, img)
    np.testing.assert_allclose(lab2, objs)


def test_det_crop_keeps_and_renormalizes_boxes():
    import random as pyrandom

    pyrandom.seed(3)
    img = np.arange(64 * 64 * 3, dtype=np.float32).reshape(64, 64, 3)
    objs = np.array([[1, 0.4, 0.4, 0.6, 0.6]], np.float32)
    aug = mximage.DetRandomCropAug(min_object_covered=0.1,
                                   area_range=(0.5, 0.9), max_attempts=50)
    out, lab = aug(img, objs)
    assert lab.shape[1] == 5
    assert ((lab[:, 1:] >= 0) & (lab[:, 1:] <= 1)).all()
    assert (lab[:, 3] > lab[:, 1]).all() and (lab[:, 4] > lab[:, 2]).all()


def _make_det_pack(tmp_path, n=16, size=64, num_classes=2):
    """Images + multi-column detection .lst -> im2rec pack -> (rec, labels)."""
    rng = np.random.RandomState(0)
    root = tmp_path / "imgs"
    os.makedirs(root, exist_ok=True)
    lines = []
    truth = []
    for i in range(n):
        img = np.zeros((size, size, 3), np.uint8)
        s = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        cls = rng.randint(0, num_classes)
        img[y0:y0 + s, x0:x0 + s, cls % 3] = 255
        fname = "im%03d.png" % i
        with open(root / fname, "wb") as f:
            f.write(image_backend.encode_image(img, ".png"))
        label = [2, 5, cls, x0 / size, y0 / size, (x0 + s) / size,
                 (y0 + s) / size]
        truth.append(label[2:])
        lines.append("%d\t%s\t%s" % (i, "\t".join("%f" % v for v in label),
                                     fname))
    prefix = str(tmp_path / "det")
    with open(prefix + ".lst", "w") as f:
        f.write("\n".join(lines) + "\n")
    subprocess.run([sys.executable, os.path.join(ROOT, "tools", "im2rec.py"),
                    prefix, str(root), "--no-shuffle", "--pass-through"],
                   check=True, capture_output=True)
    assert os.path.exists(prefix + ".rec")
    return prefix + ".rec", np.array(truth, np.float32)


def test_image_det_record_iter(tmp_path):
    rec, truth = _make_det_pack(tmp_path)
    it = mx.image.ImageDetRecordIter(
        path_imgrec=rec, data_shape=(3, 64, 64), batch_size=4,
        label_pad_width=8, std_r=255.0, std_g=255.0, std_b=255.0,
        prefetch_buffer=0, label_name="label")
    seen = 0
    for batch in it:
        data = batch.data[0].asnumpy()
        label = batch.label[0].asnumpy()
        assert data.shape == (4, 3, 64, 64)
        assert label.shape == (4, 8, 5)
        for b in range(4 - batch.pad):
            row = label[b]
            valid = row[row[:, 0] >= 0]
            assert len(valid) == 1  # one object per packed image
            np.testing.assert_allclose(valid[0], truth[seen], atol=1e-5)
            # the rectangle really is where the label says (std=255 scaling)
            cls, x1, y1, x2, y2 = valid[0]
            ch = int(cls) % 3
            xm = int((x1 + x2) / 2 * 64)
            ym = int((y1 + y2) / 2 * 64)
            assert data[b, ch, ym, xm] == pytest.approx(1.0)
            seen += 1
    assert seen == 16


def test_ssd_trains_on_det_rec(tmp_path):
    rec, _ = _make_det_pack(tmp_path)
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", "ssd",
                                      "train_ssd.py"),
         "--data-train", rec, "--num-epochs", "4", "--batch-size", "8",
         "--lr", "0.1", "--rand-mirror"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    import json

    line = [l for l in res.stdout.splitlines()
            if l.startswith('{"metric"')][-1]
    ratio = json.loads(line)["value"]
    assert ratio < 0.9, "SSD loss did not fall on .rec data: %s" % line

"""Caffe model import (mx.caffe) — the format bridge replacing the
reference's plugin/caffe + tools/caffe_converter (convert_symbol.py /
convert_model.py). Fixtures are fabricated with the module's own
wire-format writer, so neither Caffe nor protobuf is needed."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import caffe

LENET_ISH = """
name: "tiny"
input: "data"
input_dim: 2
input_dim: 3
input_dim: 8
input_dim: 8
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 stride: 1 pad: 1 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 5 } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
"""


def test_prototxt_parser_shapes():
    net = caffe.parse_prototxt(LENET_ISH)
    assert net["name"] == "tiny"
    assert net["input"] == "data"
    assert net["input_dim"] == [2, 3, 8, 8]
    layers = net["layer"]
    assert [la["type"] for la in layers] == [
        "Convolution", "ReLU", "Pooling", "InnerProduct", "Softmax"]
    assert layers[0]["convolution_param"]["num_output"] == 4


def test_wire_roundtrip():
    rng = np.random.RandomState(0)
    blobs = {"conv1": [rng.randn(4, 3, 3, 3).astype("f"),
                       rng.randn(4).astype("f")],
             "ip1": [rng.randn(5, 64).astype("f")]}
    data = caffe.encode_caffemodel(blobs)
    back = caffe.parse_caffemodel(data)
    assert set(back) == {"conv1", "ip1"}
    np.testing.assert_array_equal(back["conv1"][0], blobs["conv1"][0])
    np.testing.assert_array_equal(back["conv1"][1], blobs["conv1"][1])
    assert back["ip1"][0].shape == (5, 64)


def test_convert_and_forward_matches_manual_model():
    rng = np.random.RandomState(1)
    W = rng.randn(4, 3, 3, 3).astype("f") * 0.2
    b = rng.randn(4).astype("f") * 0.1
    Wf = rng.randn(5, 4 * 4 * 4).astype("f") * 0.2
    bf = rng.randn(5).astype("f") * 0.1
    model = caffe.encode_caffemodel(
        {"conv1": [W, b], "ip1": [Wf, bf]})

    sym, args, aux = caffe.convert_model(LENET_ISH, model)
    assert set(args) == {"conv1_weight", "conv1_bias",
                        "ip1_weight", "ip1_bias"}
    # the first conv consumes 3-channel input: the converter applies the
    # reference's BGR->RGB channel swap (convert_model.py:68-71)
    np.testing.assert_array_equal(args["conv1_weight"].asnumpy(),
                                  W[:, [2, 1, 0]])
    x = rng.randn(2, 3, 8, 8).astype("f")
    ex = sym.simple_bind(mx.cpu(), data=(2, 3, 8, 8), grad_req="null")
    for k, v in args.items():
        ex.arg_dict[k][:] = v
    out = ex.forward(is_train=False, data=x)[0].asnumpy()

    # manual oracle through the same mx ops
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, name="c", num_filter=4, kernel=(3, 3),
                             stride=(1, 1), pad=(1, 1))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", pooling_convention="full")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), name="f",
                                num_hidden=5)
    net = mx.sym.SoftmaxOutput(net, name="prob")
    ex2 = net.simple_bind(mx.cpu(), data=(2, 3, 8, 8), grad_req="null")
    ex2.arg_dict["c_weight"][:] = W[:, [2, 1, 0]]  # converter swapped BGR
    ex2.arg_dict["c_bias"][:] = b
    ex2.arg_dict["f_weight"][:] = Wf
    ex2.arg_dict["f_bias"][:] = bf
    want = ex2.forward(is_train=False, data=x)[0].asnumpy()
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_bgr_swap_first_conv_only():
    """Only the FIRST convolution (the one seeing 3/4-channel image
    input) gets the BGR->RGB swap; deeper convs keep their layout, and
    1-channel first convs are untouched."""
    proto = """
input: "data"
input_dim: 1
input_dim: 3
input_dim: 6
input_dim: 6
layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layer { name: "c2" type: "Convolution" bottom: "c1" top: "c2"
  convolution_param { num_output: 2 kernel_size: 3 pad: 1 } }
"""
    rng = np.random.RandomState(7)
    W1 = rng.randn(4, 3, 3, 3).astype("f")
    W2 = rng.randn(2, 4, 3, 3).astype("f")
    model = caffe.encode_caffemodel({"c1": [W1], "c2": [W2]})
    _, args, _ = caffe.convert_model(proto, model)
    np.testing.assert_array_equal(args["c1_weight"].asnumpy(),
                                  W1[:, [2, 1, 0]])
    np.testing.assert_array_equal(args["c2_weight"].asnumpy(), W2)

    gray = proto.replace("input_dim: 3", "input_dim: 1")
    Wg = rng.randn(4, 1, 3, 3).astype("f")
    model = caffe.encode_caffemodel(
        {"c1": [Wg], "c2": [W2]})
    _, args, _ = caffe.convert_model(gray, model)
    np.testing.assert_array_equal(args["c1_weight"].asnumpy(), Wg)


def test_blobs_absent_from_prototxt_are_skipped():
    """Train-vs-deploy mismatch: caffemodel blobs whose layer is not in
    the deploy prototxt must not leak stray params into arg_params."""
    rng = np.random.RandomState(8)
    model = caffe.encode_caffemodel({
        "conv1": [rng.randn(4, 3, 3, 3).astype("f"),
                  rng.randn(4).astype("f")],
        "ip1": [rng.randn(5, 4 * 4 * 4).astype("f")],
        "loss_only_fc": [rng.randn(2, 5).astype("f"),
                         rng.randn(2).astype("f")]})
    _, args, aux = caffe.convert_model(LENET_ISH, model)
    assert set(args) == {"conv1_weight", "conv1_bias", "ip1_weight"}
    assert not aux


def test_batchnorm_scale_merging():
    rng = np.random.RandomState(2)
    proto = """
input: "data"
input_dim: 2
input_dim: 3
input_dim: 4
input_dim: 4
layer { name: "bn" type: "BatchNorm" bottom: "data" top: "bn" }
layer { name: "sc" type: "Scale" bottom: "bn" top: "bn"
  scale_param { bias_term: true } }
layer { name: "out" type: "ReLU" bottom: "bn" top: "out" }
"""
    mean = rng.randn(3).astype("f")
    var = np.abs(rng.randn(3)).astype("f") + 1.0
    factor = np.array(2.0, "f")  # caffe stores stats scaled by 1/factor
    gamma = rng.randn(3).astype("f")
    beta = rng.randn(3).astype("f")
    model = caffe.encode_caffemodel({
        "bn": [mean * 2.0, var * 2.0, factor],
        "sc": [gamma, beta]})
    sym, args, aux = caffe.convert_model(proto, model)
    np.testing.assert_allclose(aux["bn_moving_mean"].asnumpy(), mean,
                               rtol=1e-6)
    np.testing.assert_allclose(aux["bn_moving_var"].asnumpy(), var,
                               rtol=1e-6)
    np.testing.assert_array_equal(args["bn_gamma"].asnumpy(), gamma)
    np.testing.assert_array_equal(args["bn_beta"].asnumpy(), beta)

    # forward equals the closed form (inference BN with global stats)
    x = rng.randn(2, 3, 4, 4).astype("f")
    ex = sym.simple_bind(mx.cpu(), data=(2, 3, 4, 4), grad_req="null")
    for k, v in args.items():
        ex.arg_dict[k][:] = v
    for k, v in aux.items():
        ex.aux_dict[k][:] = v
    got = ex.forward(is_train=False, data=x)[0].asnumpy()
    ref = (x - mean[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + 1e-5)
    # fix_gamma=True: caffe's BatchNorm has no gamma; Scale's gamma is
    # applied... via the merged arg — emulate mx BatchNorm fix_gamma
    ref = np.maximum(ref * gamma[None, :, None, None]
                     + beta[None, :, None, None], 0.0)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_v1_prototxt_enum_types_and_colon_brace():
    """V1 text form: `layers: { type: CONVOLUTION }` — enum names and
    the legal colon-before-brace nesting both parse and convert."""
    proto = """
input: "data"
input_dim: 1
input_dim: 1
input_dim: 6
input_dim: 6
layers: { name: "c" type: CONVOLUTION bottom: "data" top: "c"
  convolution_param { num_output: 1 kernel_size: 3 } }
layers: { name: "r" type: RELU bottom: "c" top: "r" }
layers: { name: "p" type: SOFTMAX bottom: "r" top: "p" }
"""
    rng = np.random.RandomState(3)
    W = rng.randn(1, 1, 3, 3).astype("f")
    bia = rng.randn(1).astype("f")
    model = caffe.encode_caffemodel({"c": [W, bia]})
    sym, args, aux = caffe.convert_model(proto, model)
    # num_output=1 conv weight keeps its 4D shape (no leading-1 strip)
    assert args["c_weight"].shape == (1, 1, 3, 3)
    ex = sym.simple_bind(mx.cpu(), data=(1, 1, 6, 6), grad_req="null")
    for k, v in args.items():
        ex.arg_dict[k][:] = v
    out = ex.forward(is_train=False,
                     data=rng.randn(1, 1, 6, 6).astype("f"))[0]
    assert out.shape[0] == 1


def test_eltwise_three_bottoms_and_standalone_scale():
    proto = """
input: "data"
input_dim: 2
input_dim: 3
layer { name: "e" type: "Eltwise" bottom: "data" bottom: "data"
  bottom: "data" top: "e" }
"""
    sym, _ = caffe.convert_symbol(proto)
    ex = sym.simple_bind(mx.cpu(), data=(2, 3), grad_req="null")
    x = np.ones((2, 3), "f")
    out = ex.forward(is_train=False, data=x)[0].asnumpy()
    np.testing.assert_allclose(out, 3 * x)  # all three bottoms summed

    bad = proto.replace(
        'type: "Eltwise" bottom: "data" bottom: "data"\n  bottom: "data"',
        'type: "Scale" bottom: "data"')
    import pytest

    with pytest.raises(NotImplementedError, match="standalone Scale"):
        caffe.convert_symbol(bad)


def test_caffeop_single_layer_sugar():
    """Runtime parity with plugin/caffe CaffeOp: embed one prototxt
    layer spec in a native graph."""
    net = mx.sym.Variable("data")
    net = mx.caffe.CaffeOp(net, 'layer { name: "c1" type: "Convolution" '
                                'convolution_param { num_output: 2 '
                                'kernel_size: 3 pad: 1 } }')
    net = mx.caffe.CaffeOp(net, 'layer { type: "ReLU" }', name="r1")
    ex = net.simple_bind(mx.cpu(), data=(1, 3, 5, 5), grad_req="null")
    rng = np.random.RandomState(0)
    for k in ex.arg_dict:
        if k != "data":
            ex.arg_dict[k][:] = rng.randn(
                *ex.arg_dict[k].shape).astype("f") * 0.1
    out = ex.forward(is_train=False,
                     data=rng.randn(1, 3, 5, 5).astype("f"))[0].asnumpy()
    assert out.shape == (1, 2, 5, 5)
    assert (out >= 0).all()  # the ReLU layer applied


def test_convert_mean():
    # shape-field encoding: num=1 leading dim squeezed to (C, H, W)
    arr = np.arange(12, dtype="f").reshape(1, 3, 2, 2)
    back = caffe.convert_mean(caffe.encode_blob(arr))
    np.testing.assert_array_equal(back, arr[0])
    # legacy num/channels/height/width dims (what real mean files use)
    data = arr.ravel().tobytes()
    legacy = (caffe._enc_field(1, 0, caffe._enc_varint(1))
              + caffe._enc_field(2, 0, caffe._enc_varint(3))
              + caffe._enc_field(3, 0, caffe._enc_varint(2))
              + caffe._enc_field(4, 0, caffe._enc_varint(2))
              + caffe._enc_field(5, 2,
                                 caffe._enc_varint(len(data)) + data))
    back = caffe.convert_mean(legacy)
    assert back.shape == (3, 2, 2)
    np.testing.assert_array_equal(back, arr[0])


def test_caffeop_unnamed_layers_get_unique_params():
    net = mx.sym.Variable("data")
    net = mx.caffe.CaffeOp(net, 'layer { type: "Convolution" '
                                'convolution_param { num_output: 2 '
                                'kernel_size: 1 } }')
    net = mx.caffe.CaffeOp(net, 'layer { type: "Convolution" '
                                'convolution_param { num_output: 2 '
                                'kernel_size: 1 } }')
    args = net.list_arguments()
    weights = [a for a in args if a.endswith("_weight")]
    assert len(weights) == 2 and weights[0] != weights[1], args


def test_v1_layers_field_and_legacy_blob_dims():
    """V1 NetParameter uses field 2 (layers), name=4, blobs=6, and
    legacy num/channels/height/width blob dims."""
    W = np.arange(6, dtype="f").reshape(2, 3)
    nm = b"fc"
    blob = (caffe._enc_field(1, 0, caffe._enc_varint(1))
            + caffe._enc_field(2, 0, caffe._enc_varint(1))
            + caffe._enc_field(3, 0, caffe._enc_varint(2))
            + caffe._enc_field(4, 0, caffe._enc_varint(3)))
    data = W.ravel().tobytes()
    blob += caffe._enc_field(5, 2, caffe._enc_varint(len(data)) + data)
    layer = (caffe._enc_field(4, 2, caffe._enc_varint(len(nm)) + nm)
             + caffe._enc_field(6, 2,
                                caffe._enc_varint(len(blob)) + blob))
    net = caffe._enc_field(2, 2, caffe._enc_varint(len(layer)) + layer)
    out = caffe.parse_caffemodel(net)
    # legacy 4D dims are preserved verbatim (the layer-aware conversion
    # squeezes fc weights to the trailing matrix)
    assert out["fc"][0].shape == (1, 1, 2, 3)
    np.testing.assert_array_equal(out["fc"][0].reshape(2, 3), W)

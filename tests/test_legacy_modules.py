"""Legacy/utility module parity: executor_manager, log, misc, libinfo,
_ndarray_internal/_symbol_internal, ndarray_doc/symbol_doc (reference
python/mxnet counterparts)."""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.executor_manager import (DataParallelExecutorManager,
                                        _check_arguments,
                                        _split_input_slice)


def test_split_input_slice():
    assert _split_input_slice(10, [1, 1]) == [slice(0, 5), slice(5, 10)]
    s = _split_input_slice(9, [1, 2])
    assert s[0].stop == s[1].start and s[-1].stop == 9
    with pytest.raises(ValueError):
        _split_input_slice(2, [1, 1, 1])  # an empty split


def test_check_arguments_duplicates():
    # two DISTINCT variables with the same name (one shared node would be
    # legitimately deduplicated)
    net = mx.sym.elemwise_add(
        mx.sym.FullyConnected(mx.sym.Variable("data"),
                              weight=mx.sym.Variable("w"),
                              num_hidden=4, no_bias=True, name="fc1"),
        mx.sym.FullyConnected(mx.sym.Variable("data2"),
                              weight=mx.sym.Variable("w"),
                              num_hidden=4, no_bias=True, name="fc2"))
    with pytest.raises(ValueError, match="duplicated"):
        _check_arguments(net)


def test_executor_manager_step():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    man = DataParallelExecutorManager(net, [mx.cpu(0), mx.cpu(1)], it)
    man.set_params({"fc_weight": mx.nd.array(
        rng.randn(2, 6).astype("f") * 0.2), "fc_bias": mx.nd.zeros((2,))},
        {})
    batch = it.next()
    man.load_data_batch(batch)
    man.forward(is_train=True)
    man.backward()
    metric = mx.metric.Accuracy()
    man.update_metric(metric, batch.label)
    assert 0.0 <= metric.get()[1] <= 1.0
    assert man.param_names == ["fc_weight", "fc_bias"]
    assert len(man.grad_arrays) == 2
    out_params = {"fc_weight": mx.nd.zeros((2, 6)),
                  "fc_bias": mx.nd.zeros((2,))}
    man.copy_to(out_params, {})
    assert float(np.abs(out_params["fc_weight"].asnumpy()).sum()) > 0


def test_log_module(tmp_path):
    logger = mx.log.get_logger("t_parity", level=mx.log.DEBUG)
    assert logger.level == logging.DEBUG
    f = tmp_path / "x.log"
    flog = mx.log.get_logger("t_file", filename=str(f), level=mx.log.INFO)
    flog.info("hello-parity")
    for h in flog.handlers:
        h.flush()
    assert "hello-parity" in f.read_text()


def test_misc_factor_scheduler():
    from mxnet_tpu.misc import FactorScheduler

    sched = FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(0) == 1.0
    assert sched(10) == 0.5
    assert sched(25) == 0.25
    with pytest.raises(ValueError):
        FactorScheduler(step=0)


def test_libinfo_paths():
    from mxnet_tpu import libinfo

    paths = libinfo.find_lib_path()
    assert all(p.endswith(".so") for p in paths)


def test_internal_namespaces():
    from mxnet_tpu import _ndarray_internal, _symbol_internal

    out = _ndarray_internal._plus_scalar(mx.nd.ones((2,)), scalar=3.0)
    np.testing.assert_allclose(out.asnumpy(), [4, 4])
    s = _symbol_internal._plus_scalar(mx.sym.Variable("x"), scalar=1.0)
    assert s.list_arguments() == ["x"]
    with pytest.raises(AttributeError):
        _ndarray_internal._no_such_op_xyz


def test_doc_registries():
    from mxnet_tpu import ndarray_doc, symbol_doc

    class FullyConnected(ndarray_doc.NDArrayDoc):
        """Extra FC doc."""

    assert ndarray_doc.get_extra_doc("FullyConnected") == "Extra FC doc."
    shapes = symbol_doc.SymbolDoc.get_output_shape(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3),
        data=(2, 5))
    assert list(shapes.values())[0] == (2, 3)


def test_check_speed():
    from mxnet_tpu.test_utils import check_speed

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4),
        name="softmax")
    t_whole = check_speed(net, N=2, data=(8, 5), softmax_label=(8,))
    t_fwd = check_speed(net, N=2, typ="forward", data=(8, 5),
                        softmax_label=(8,))
    assert t_whole > 0 and t_fwd > 0
    with pytest.raises(ValueError, match="typ"):
        check_speed(net, N=1, typ="bogus", data=(8, 5), softmax_label=(8,))

"""perf_probe analysis units: the BN-epilogue classifier must answer by
dataflow, not substring presence (VERDICT r4: settle whether BN scale/
shift rides the conv epilogue in the committed HLO)."""
import os
import sys

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, os.path.join(ROOT, "tools"))


def test_bn_fusion_analysis_dataflow():
    from perf_probe import bn_fusion_analysis

    synthetic = """HloModule m

%fused_computation.1 (p0: f32[4], p1: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %p1 = f32[4]{0} parameter(1)
  %convolution.1 = f32[4]{0} convolution(%p0, %p1), window={}
  %mul.1 = f32[4]{0} multiply(%convolution.1, %p1)
  ROOT %add.1 = f32[4]{0} add(%mul.1, %p0)
}

%fused_computation.2 (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  %scaled = f32[4]{0} multiply(%p0, %p0)
  ROOT %convolution.2 = f32[4]{0} convolution(%scaled, %p0), window={}
}

ENTRY %main (a: f32[4]) -> f32[4] {
  %a = f32[4]{0} parameter(0)
  %convolution.3 = f32[4]{0} convolution(%a, %a), window={}
  %s = f32[4]{0} add(%convolution.3, %a)
  ROOT %f = f32[4]{0} fusion(%s), kind=kLoop, calls=%fused_computation.1
}
"""
    r = bn_fusion_analysis(synthetic)
    # conv.1: result consumed by multiply in its fusion -> epilogue-fused.
    # conv.2: multiply feeds the conv INPUT; result untouched -> plain.
    # conv.3: lives in ENTRY -> bare, even with an entry-level add consumer
    # (entry instructions are separate kernels).
    assert r == {"convs_total": 3,
                 "convs_fused_with_elementwise_epilogue": 1,
                 "convs_fused_plain": 1,
                 "convs_bare_in_entry": 1}, r

    # modern compiled.as_text() dumps omit the % name sigil entirely —
    # classification must be identical on the sigil-less form
    r2 = bn_fusion_analysis(synthetic.replace("%", ""))
    assert r2 == r, r2

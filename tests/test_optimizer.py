"""Optimizer update rules vs numpy references
(reference: tests/python/unittest/test_optimizer.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def _run_updates(optr, w0, g, n=3):
    w = nd.array(w0.copy())
    state = optr.create_state(0, w)
    for _ in range(n):
        optr.update(0, w, nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w0 = np.random.randn(4, 3).astype(np.float32)
    g = np.random.randn(4, 3).astype(np.float32)
    lr, wd = 0.1, 0.01
    out = _run_updates(mx.optimizer.SGD(learning_rate=lr, wd=wd,
                                        rescale_grad=1.0), w0, g)
    w = w0.copy()
    for _ in range(3):
        w -= lr * (g + wd * w)
    assert_almost_equal(out, w, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_matches_numpy():
    w0 = np.random.randn(5).astype(np.float32)
    g = np.random.randn(5).astype(np.float32)
    lr, mom, wd = 0.1, 0.9, 0.0
    out = _run_updates(mx.optimizer.SGD(learning_rate=lr, momentum=mom,
                                        wd=wd, rescale_grad=1.0), w0, g)
    w, v = w0.copy(), np.zeros_like(w0)
    for _ in range(3):
        # reference sgd_mom_update (optimizer_op-inl.h): v = m*v - lr*(g+wd*w)
        v = mom * v - lr * (g + wd * w)
        w += v
    assert_almost_equal(out, w, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    w0 = np.random.randn(6).astype(np.float32)
    g = np.random.randn(6).astype(np.float32)
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    out = _run_updates(mx.optimizer.Adam(learning_rate=lr, beta1=b1, beta2=b2,
                                         epsilon=eps, wd=0.0,
                                         rescale_grad=1.0), w0, g)
    w = w0.copy()
    m = np.zeros_like(w0)
    v = np.zeros_like(w0)
    for t in range(1, 4):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w -= lr_t * m / (np.sqrt(v) + eps)
    assert_almost_equal(out, w, rtol=1e-4, atol=1e-5)


def test_rmsprop_runs_and_descends():
    # loss = 0.5*||w||^2, grad = w: every optimizer should shrink the norm
    for name in ["sgd", "adam", "rmsprop", "adagrad", "adadelta", "nag",
                 "sgld", "dcasgd"]:
        optr = mx.optimizer.Optimizer.create_optimizer(
            name, learning_rate=0.05, rescale_grad=1.0)
        w = nd.array(np.ones(8, np.float32) * 5.0)
        state = optr.create_state(0, w)
        for _ in range(20):
            optr.update(0, w, w.copy(), state)
        final = np.abs(w.asnumpy()).mean()
        assert final < 5.0, "%s did not descend (|w|=%f)" % (name, final)


def test_lr_mult_and_wd_mult():
    opt = mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0)
    opt.set_lr_mult({"frozen": 0.0})
    opt.idx2name = {0: "frozen", 1: "free"}
    w_frozen = nd.array(np.ones(3, np.float32))
    w_free = nd.array(np.ones(3, np.float32))
    g = nd.array(np.ones(3, np.float32))
    opt.update(0, w_frozen, g, opt.create_state(0, w_frozen))
    opt.update(1, w_free, g, opt.create_state(1, w_free))
    assert_almost_equal(w_frozen, np.ones(3))
    assert float(np.abs(w_free.asnumpy() - 1.0).sum()) > 0


def test_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    # reference lr_scheduler.py:36 drops lr only when num_update exceeds the
    # step boundary (strict >)
    assert abs(sched(5) - 1.0) < 1e-6
    assert abs(sched(11) - 0.5) < 1e-6
    assert abs(sched(25) - 0.25) < 1e-6
    msched = mx.lr_scheduler.MultiFactorScheduler(step=[5, 15], factor=0.1)
    msched.base_lr = 1.0
    assert abs(msched(4) - 1.0) < 1e-6
    assert abs(msched(6) - 0.1) < 1e-6
    assert abs(msched(20) - 0.01) < 1e-6


def test_updater_closure():
    opt = mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0)
    updater = mx.optimizer.get_updater(opt)
    w = nd.array(np.ones(4, np.float32))
    g = nd.array(np.full(4, 2.0, np.float32))
    updater(0, g, w)
    assert_almost_equal(w, np.ones(4) - 0.1 * 2.0, rtol=1e-5, atol=1e-6)


def test_clip_gradient():
    opt = mx.optimizer.SGD(learning_rate=1.0, clip_gradient=0.5,
                           rescale_grad=1.0, wd=0.0)
    w = nd.array(np.zeros(2, np.float32))
    g = nd.array(np.array([10.0, -10.0], np.float32))
    opt.update(0, w, g, opt.create_state(0, w))
    assert_almost_equal(w, [-0.5, 0.5], rtol=1e-5, atol=1e-6)

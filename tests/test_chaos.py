"""Chaos tests — training survives injected faults and process kills.

Every test here is deterministic (seeded FaultPlan, or single-fault count
triggers) and fast enough for tier-1; replay a failing configuration with
``tools/chaos_run.py``.  The dist_sync-semantics convergence test drives
the sync-mode KVStoreServer (server-mediated synchronous data
parallelism) through the crash-tolerant ServerClient transport.
"""

import contextlib
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu import kvstore_server as kvs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    """Tight retry/backoff so injected faults resolve in milliseconds."""
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "40")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "20")


def test_retried_push_after_dropped_ack_applied_exactly_once():
    """The tentpole's exactly-once guarantee: the ACK of a push is dropped
    on the wire, the client reconnects and replays the same idempotency
    token, and the server must NOT apply the push a second time."""
    srv = kvs.start_server(num_workers=1)
    host, port = srv.addr
    try:
        # kv.client.recv fires before the reply is read: #1 is the init
        # ACK, #2 the push ACK — the server has already applied the push
        # when the drop hits, which is exactly the dangerous case
        with faults.inject("kv.client.recv:drop=1@#2") as plan:
            with kvs.ServerClient(host, port) as c:
                c.init(0, np.full(4, 10.0, np.float32))
                c.push(0, np.full(4, 5.0, np.float32))
                out = c.pull(0)
            assert plan.events == [("kv.client.recv", "drop", 2)]
        np.testing.assert_array_equal(out, np.full(4, 15.0, np.float32))
        assert srv.applied_pushes == 1  # replay was deduplicated
    finally:
        srv.stop()


def _run_sync_training(steps=8, spec=None, seed=0):
    """Two worker threads training one key against a sync-mode server
    (dist_sync semantics: per-round merge of one push per worker, then the
    SGD update fires).  Returns the final pulled weights."""
    srv = kvs.start_server(num_workers=2, sync_mode=True)
    host, port = srv.addr
    ctx = faults.inject(spec, seed) if spec else contextlib.nullcontext()
    try:
        with ctx:
            clients = [kvs.ServerClient(host, port) for _ in range(2)]
            clients[0].init(0, np.zeros(4, np.float32))
            clients[0].set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
            errs = []

            def worker(rank):
                try:
                    rng = np.random.RandomState(100 + rank)
                    for _ in range(steps):
                        grad = rng.randn(4).astype(np.float32)
                        clients[rank].push(0, grad, rank=rank)
                        clients[rank].barrier(rank=rank)
                except Exception as e:  # pragma: no cover - fail loudly
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not errs, errs
            out = clients[0].pull(0)
            for c in clients:
                c.close()
            return out
    finally:
        srv.stop()


def test_sync_training_converges_under_30pct_connection_drops():
    """Acceptance: with 30% of every worker wire op (connect/send/recv)
    dropping, retry + idempotent replay must land the job on EXACTLY the
    weights of the fault-free run — every push applied once, no round
    skipped or doubled."""
    clean = _run_sync_training(spec=None)
    faulty = _run_sync_training(spec="kv.client.*:drop=0.3", seed=7)
    np.testing.assert_array_equal(clean, faulty)


def test_server_kill_restart_resumes_from_snapshot(tmp_path, monkeypatch):
    """Acceptance: SIGKILL the kvstore server mid-training, respawn it
    with the same snapshot path (what tools/launch.py --auto-resume does),
    and the job finishes with the exact fault-free result — the workers
    never restart, their transport just rides out the outage."""
    # the restarted server needs to import jax before it listens: give the
    # replayed RPCs a long backoff runway
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "120")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "10")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "500")
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    snap = str(tmp_path / "kv.snap")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("DMLC_ROLE", None)

    def spawn():
        return subprocess.Popen(
            [sys.executable, os.path.join(ROOT, "tests",
                                          "chaos_kv_server.py"),
             "127.0.0.1", str(port), snap],
            env=env, cwd=ROOT)

    server = spawn()
    try:
        c = kvs.ServerClient("127.0.0.1", port)  # retries until it is up
        c.init(0, np.zeros(4, np.float32))
        for i in range(1, 4):
            c.push(0, np.full(4, float(i), np.float32))
        # quiesce point: force a durable snapshot, then the kill is safe
        assert c.snapshot() == snap
        assert os.path.exists(snap) and os.path.exists(snap + ".crc32")
        server.kill()  # SIGKILL: no cleanup, no farewell snapshot
        server.wait(timeout=30)
        server = spawn()
        for i in range(4, 7):  # training continues against the ghost...
            c.push(0, np.full(4, float(i), np.float32))
        out = c.pull(0)
        # accumulate mode: 1+2+3 survived the kill via the snapshot,
        # 4+5+6 landed on the restarted server — nothing lost or doubled
        np.testing.assert_array_equal(out, np.full(4, 21.0, np.float32))
        c.stop_server()
        c.close()
        assert server.wait(timeout=30) == 0
    finally:
        if server.poll() is None:
            server.kill()


def test_server_snapshot_restore_roundtrip(tmp_path):
    """In-process snapshot/restore: store, updater (with live momentum),
    and barrier generation survive; CRC-corrupt snapshots cold-start."""
    snap = str(tmp_path / "kv.snap")
    srv = kvs.KVStoreServer(port=0, num_workers=1, snapshot_path=snap,
                            snapshot_interval=0)
    srv.start_background()
    host, port = srv.addr
    with kvs.ServerClient(host, port) as c:
        c.init("w", np.zeros(3, np.float32))
        c.set_optimizer(mx.optimizer.SGD(learning_rate=1.0, momentum=0.9))
        c.push("w", np.ones(3, np.float32))
        c.barrier()
        after_one = np.array(c.pull("w"))
        c.stop_server()  # snapshots on stop
    srv2 = kvs.KVStoreServer(port=0, num_workers=1, snapshot_path=snap,
                             snapshot_interval=0)
    srv2.start_background()
    try:
        assert srv2.restored
        assert srv2._barrier_gen == 1
        host2, port2 = srv2.addr
        with kvs.ServerClient(host2, port2) as c2:
            np.testing.assert_array_equal(np.array(c2.pull("w")), after_one)
            # momentum survived the restart: the second unit-gradient step
            # must move FARTHER than the first (velocity accumulated)
            c2.push("w", np.ones(3, np.float32))
            after_two = np.array(c2.pull("w"))
        step2 = np.abs(after_two - after_one)
        step1 = np.abs(after_one)
        assert (step2 > step1).all()
    finally:
        srv2.stop()
    # a corrupted snapshot is skipped, not fatal
    with open(snap, "r+b") as f:
        f.write(b"\xff\xff\xff\xff")
    srv3 = kvs.KVStoreServer(port=0, num_workers=1, snapshot_path=snap,
                             snapshot_interval=0)
    assert not srv3.restored and srv3.store == {}
    srv3._server.server_close()


def test_periodic_snapshot_thread_writes_without_traffic(tmp_path):
    snap = str(tmp_path / "kv.snap")
    srv = kvs.KVStoreServer(port=0, num_workers=1, snapshot_path=snap,
                            snapshot_interval=0.05)
    srv.start_background()
    try:
        host, port = srv.addr
        with kvs.ServerClient(host, port) as c:
            c.init(0, np.ones(2, np.float32))
            deadline = time.monotonic() + 5.0
            while not os.path.exists(snap):
                assert time.monotonic() < deadline, "no periodic snapshot"
                time.sleep(0.02)
    finally:
        srv.stop()
    from mxnet_tpu.filesystem import verify_crc_sidecar

    assert verify_crc_sidecar(snap) is True


def test_sdc_rollback_scenario_replays_bit_identical():
    """The guardian acceptance scenario end to end: a seeded exponent
    bit-flip in one gradient tensor is detected by the step guard, the
    fit rolls back to the last-good ring snapshot (params + updater +
    PRNG + iterator cursor) and replays to a final state bit-identical
    to an uninjected control run; a NaN-poisoned kvstore push is NACKed
    server-side and never applied.  Replay other schedules with
    ``python tools/chaos_run.py --scenario sdc-rollback --seeds 0:N``."""
    tools = os.path.join(ROOT, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    from chaos_run import run_sdc_rollback

    assert run_sdc_rollback(seed=0, timeout=110.0)

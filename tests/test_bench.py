"""The driver-facing bench entry: orchestration, phase records, and
failure normalization (all CPU-safe; the TPU paths differ only in which
branches the phase children take).

Reference equivalent for the record shape:
example/image-classification/train_imagenet.py --benchmark 1 prints the
steady-state img/s the same way (common/fit.py:106-116)."""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench  # noqa: E402

def _cli(extra=()):
    return bench._arg_parser().parse_args(list(extra))

def test_headline_prefers_lm_mfu():
    rec = {"metric": "resnet50_train_throughput", "value": 2400.0,
           "unit": "img/s", "vs_baseline": 13.2,
           "transformer_lm_mfu": 0.514, "transformer_lm_attn": "flash"}
    out = bench._headline(dict(rec))
    assert out["metric"] == "transformer_lm_train_mfu"
    assert out["value"] == 0.514
    assert out["vs_baseline"] == round(0.514 / bench.LM_NORTH_STAR, 3)
    # the parity track stays visible
    assert out["resnet50_img_per_sec"] == 2400.0
    assert out["resnet50_vs_p100"] == 13.2

def test_headline_falls_back_to_resnet():
    rec = {"metric": "resnet50_train_throughput", "value": 2400.0,
           "unit": "img/s", "vs_baseline": 13.2}
    assert bench._headline(dict(rec)) == rec

def test_run_phase_normalizes_child_error(monkeypatch):
    """A crashed child's fallback JSON (metric/value/error keys) must not
    contaminate the merged record — only <phase>_error survives."""
    fake = json.dumps({"metric": "transformer_lm_train_mfu", "value": 0.0,
                       "unit": "MFU", "vs_baseline": 0.0,
                       "error": "RuntimeError: boom"})

    def fake_run(*a, **k):
        return subprocess.CompletedProcess(a, 1, stdout=fake + "\n",
                                           stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench._run_phase("lm", _cli(), timeout=5)
    assert set(out) == {"lm_error"}
    assert "boom" in out["lm_error"]

def test_run_phase_normalizes_timeout(monkeypatch):
    def fake_run(*a, **k):
        raise subprocess.TimeoutExpired(cmd=a, timeout=k.get("timeout"))

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    out = bench._run_phase("resnet", _cli(), timeout=7)
    assert set(out) == {"resnet_error"}
    assert "7" in out["resnet_error"]

def test_run_phase_parses_last_json_line(monkeypatch):
    ok = {"backend": "tpu", "transformer_lm_mfu": 0.4}

    def fake_run(*a, **k):
        return subprocess.CompletedProcess(
            a, 0, stdout="noise\n" + json.dumps(ok) + "\n", stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._run_phase("lm", _cli(), timeout=5) == ok

def test_run_phase_passthrough_flags(monkeypatch):
    seen = {}

    def fake_run(cmd, **k):
        seen["cmd"] = cmd
        return subprocess.CompletedProcess(cmd, 0, stdout="{}", stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    bench._run_phase("resnet", _cli(["--skip-transformer",
                                     "--skip-attention",
                                     "--lm-attn", "splash"]), timeout=5)
    cmd = seen["cmd"]
    assert "--skip-transformer" in cmd and "--skip-attention" in cmd
    assert cmd[cmd.index("--lm-attn") + 1] == "splash"
    assert cmd[cmd.index("--phase") + 1] == "resnet"

def test_lm_phase_skips_off_tpu():
    """Real subprocess: on the CPU test platform the lm phase reports
    lm_skipped rather than hanging or crashing."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--phase", "lm"],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec == {"backend": "cpu", "lm_skipped": "backend cpu"}

"""URI-scheme filesystem registry (reference: dmlc InputSplit URI
resolution, make/config.mk:136-144 USE_HDFS/USE_S3 build gates —
runtime-registered openers here)."""
import io as pyio

import numpy as np
import pytest

import mxnet_tpu as mx


class _MemFS:
    """In-memory scheme handler: enough file-like surface for RecordIO
    (read/write/seek/tell/close) in binary and text modes."""

    def __init__(self):
        self.store = {}

    def __call__(self, uri, mode):
        if "w" in mode:
            outer = self

            class _W(pyio.BytesIO):
                def close(inner):
                    outer.store[uri] = inner.getvalue()
                    super().close()

            w = _W()
            return w if "b" in mode else pyio.TextIOWrapper(w)
        data = self.store[uri]
        return (pyio.BytesIO(data) if "b" in mode
                else pyio.StringIO(data.decode()))


def test_unregistered_scheme_raises_with_hint():
    with pytest.raises(IOError, match="USE_S3"):
        mx.filesystem.open_uri("s3://bucket/train.rec", "rb")
    with pytest.raises(IOError, match="register_scheme"):
        mx.filesystem.open_uri("weird://x/y", "rb")


def test_non_dispatchable_schemes_rejected():
    for bad in ("", "file", "a"):
        with pytest.raises(ValueError, match="cannot be registered"):
            mx.filesystem.register_scheme(bad, lambda uri, mode: None)


def test_plain_and_file_paths_are_local(tmp_path):
    p = tmp_path / "x.bin"
    with mx.filesystem.open_uri(str(p), "wb") as f:
        f.write(b"abc")
    with mx.filesystem.open_uri("file://" + str(p), "rb") as f:
        assert f.read() == b"abc"
    # a Windows drive letter is not a scheme
    assert mx.filesystem.scheme_of("C://nope") == ""
    assert mx.filesystem.scheme_of("hdfs://nn/x") == "hdfs"


def test_recordio_roundtrip_through_registered_scheme():
    fs = _MemFS()
    mx.filesystem.register_scheme("mem", fs)
    try:
        w = mx.recordio.MXRecordIO("mem://d/train.rec", "w")
        payloads = [bytes([i]) * (100 + i) for i in range(5)]
        for p in payloads:
            w.write(p)
        w.close()
        assert "mem://d/train.rec" in fs.store

        r = mx.recordio.MXRecordIO("mem://d/train.rec", "r")
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(bytes(rec))
        assert got == payloads
    finally:
        mx.filesystem.register_scheme("mem", None)


def test_indexed_recordio_through_registered_scheme():
    fs = _MemFS()
    mx.filesystem.register_scheme("mem", fs)
    try:
        w = mx.recordio.MXIndexedRecordIO(
            "mem://d/t.idx", "mem://d/t.rec", "w")
        for i in range(4):
            w.write_idx(i, b"r%d" % i * 20)
        w.close()
        r = mx.recordio.MXIndexedRecordIO(
            "mem://d/t.idx", "mem://d/t.rec", "r")
        assert bytes(r.read_idx(2)) == b"r2" * 20
        assert r.keys == [0, 1, 2, 3]
    finally:
        mx.filesystem.register_scheme("mem", None)


def test_image_record_iter_through_registered_scheme():
    """End to end: pack a tiny image .rec into the mem scheme, train-read
    it through ImageRecordIter (Python handle path; the native fast path
    is local-only by design)."""
    fs = _MemFS()
    mx.filesystem.register_scheme("mem", fs)
    try:
        from PIL import Image

        w = mx.recordio.MXIndexedRecordIO(
            "mem://d/i.idx", "mem://d/i.rec", "w")
        rng = np.random.RandomState(0)
        for i in range(6):
            img = Image.fromarray(
                rng.randint(0, 255, (32, 32, 3), dtype=np.uint8))
            buf = pyio.BytesIO()
            img.save(buf, format="JPEG")
            header = mx.recordio.IRHeader(0, float(i % 3), i, 0)
            w.write_idx(i, mx.recordio.pack(header, buf.getvalue()))
        w.close()

        it = mx.image.ImageIter(
            batch_size=2, data_shape=(3, 32, 32),
            path_imgrec="mem://d/i.rec", path_imgidx="mem://d/i.idx",
            shuffle=False)
        # the explicitly passed remote idx must be honored (indexed
        # reader, not a sequential-scan fallback)
        assert isinstance(it.record, mx.recordio.MXIndexedRecordIO)
        assert it.record.keys == list(range(6))
        batch = it.next()
        assert batch.data[0].shape == (2, 3, 32, 32)
    finally:
        mx.filesystem.register_scheme("mem", None)


def test_imglist_iter_constructs_on_native_hosts(tmp_path):
    """Regression: reset()'s native gating must tolerate _rec_path=None
    (imglist mode) wherever the C++ fast path is available."""
    from PIL import Image

    rng = np.random.RandomState(0)
    names = []
    for i in range(4):
        name = "img%d.jpg" % i
        Image.fromarray(
            rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)).save(
            str(tmp_path / name))
        names.append(name)
    it = mx.image.ImageIter(
        batch_size=2, data_shape=(3, 32, 32), path_root=str(tmp_path),
        imglist=[[float(i % 2), n] for i, n in enumerate(names)])
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 32, 32)

"""Data-parallel execution over the 8-virtual-device CPU mesh
(reference: tests/python/unittest/test_multi_device_exec.py +
executor_group slicing semantics; here the mesh replaces per-device
executors and XLA inserts the gradient reduction)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def _devices():
    import jax
    return jax.devices()


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_eight_virtual_devices_present():
    assert len(_devices()) >= 8, \
        "conftest must force 8 virtual CPU devices"


def test_dp_forward_matches_single_device():
    n_dev = 8
    ctxs = [mx.cpu(i) for i in range(n_dev)]
    X = np.random.randn(16, 10).astype(np.float32)
    y = np.zeros(16, np.float32)

    mod1 = mx.mod.Module(_mlp(), label_names=("softmax_label",),
                         context=mx.cpu(0))
    mod1.bind(data_shapes=[("data", (16, 10))],
              label_shapes=[("softmax_label", (16,))])
    mod1.init_params(mx.init.Xavier(rnd_type="uniform", magnitude=2.0))

    modN = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    modN.bind(data_shapes=[("data", (16, 10))],
              label_shapes=[("softmax_label", (16,))])
    arg, aux = mod1.get_params()
    modN.set_params(arg, aux)

    batch = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(y)])
    mod1.forward(batch, is_train=False)
    modN.forward(batch, is_train=False)
    assert_almost_equal(mod1.get_outputs()[0], modN.get_outputs()[0],
                        rtol=1e-5, atol=1e-6)


def test_dp_gradients_match_single_device():
    ctxs = [mx.cpu(i) for i in range(8)]
    X = np.random.randn(16, 10).astype(np.float32)
    y = (np.arange(16) % 4).astype(np.float32)

    def run(mod):
        mod.bind(data_shapes=[("data", (16, 10))],
                 label_shapes=[("softmax_label", (16,))], for_training=True)
        mod.init_params(mx.init.Uniform(0.1))
        return mod

    mod1 = run(mx.mod.Module(_mlp(), label_names=("softmax_label",),
                             context=mx.cpu(0)))
    modN = run(mx.mod.Module(_mlp(), label_names=("softmax_label",),
                             context=ctxs))
    arg, aux = mod1.get_params()
    modN.set_params(arg, aux)

    batch = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(y)])
    for mod in (mod1, modN):
        mod.forward(batch, is_train=True)
        mod.backward()
    g1 = mod1._exec_group.execs[0].grad_dict
    gN = modN._exec_group.execs[0].grad_dict
    for name in g1:
        assert_almost_equal(g1[name], gN[name], rtol=1e-4, atol=1e-5,
                            names=("single[%s]" % name, "mesh[%s]" % name))


def test_dp_batch_is_sharded_params_replicated():
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    mod.bind(data_shapes=[("data", (32, 10))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params()
    exe = mod._exec_group.execs[0]
    data_sh = exe.arg_dict["data"]._data.sharding
    w_sh = exe.arg_dict["fc1_weight"]._data.sharding
    assert not data_sh.is_fully_replicated
    assert w_sh.is_fully_replicated


def test_dp_fit_converges():
    rng = np.random.RandomState(3)
    X = rng.randn(256, 10).astype(np.float32)
    w = rng.randn(10, 4).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    ctxs = [mx.cpu(i) for i in range(8)]
    train = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    mod.fit(train, num_epoch=25, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            kvstore="device")
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=64),
                      mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc >= 0.9, "DP fit under-converged: %f" % acc


def test_indivisible_batch_raises():
    ctxs = [mx.cpu(i) for i in range(3)]
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    with pytest.raises(mx.MXNetError):
        mod.bind(data_shapes=[("data", (16, 10))],
                 label_shapes=[("softmax_label", (16,))])


def test_group2ctx_model_parallel():
    """Reference tests/python/unittest/test_model_parallel.py: place graph
    stages on different devices via group2ctx; values and grads must match
    single-device execution (cross-device copies are jax.device_put compiled
    into the step)."""
    n, d = 8, 6
    with mx.AttrScope(ctx_group="dev1"):
        data = sym.Variable("data")
        fc1 = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
        act1 = sym.Activation(data=fc1, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = sym.FullyConnected(data=act1, num_hidden=4, name="fc2")
        net = sym.SoftmaxOutput(data=fc2, name="softmax")

    import jax
    assert len(jax.devices()) >= 2
    group2ctx = {"dev1": mx.Context("cpu", 0), "dev2": mx.Context("cpu", 1)}
    x = np.random.uniform(-1, 1, (n, d)).astype(np.float32)
    lab = np.random.randint(0, 4, (n,)).astype(np.float32)

    def run(g2c):
        exe = net.simple_bind(mx.cpu(), data=(n, d), grad_req="write",
                              group2ctx=g2c)
        if g2c:
            # guard against the placement map silently coming back empty
            assert len(exe._placement) >= 2, \
                "group2ctx produced no placements: %r" % (exe._placement,)
            assert len(set(exe._placement.values())) == 2
        rng = np.random.RandomState(0)
        for name, arr in exe.arg_dict.items():
            if name in ("data", "softmax_label"):
                continue
            arr[:] = rng.uniform(-0.1, 0.1, arr.shape).astype(np.float32)
        exe.arg_dict["data"][:] = x
        exe.arg_dict["softmax_label"][:] = lab
        out = exe.forward(is_train=True)[0].asnumpy()
        exe.backward()
        return out, {k: v.asnumpy() for k, v in exe.grad_dict.items()
                     if v is not None}

    out_mp, grads_mp = run(group2ctx)
    out_sd, grads_sd = run(None)
    assert_almost_equal(out_mp, out_sd, rtol=1e-5, atol=1e-6)
    for k in grads_sd:
        assert_almost_equal(grads_mp[k], grads_sd[k], rtol=1e-5, atol=1e-6)


def test_executor_set_shardings_tensor_parallel():
    """Tensor parallelism through the product surface: FullyConnected
    weights sharded on a 'model' mesh axis via Executor.set_shardings;
    outputs and gradients must match an unsharded executor, and the
    weight must actually live sharded on the mesh."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(_devices()[:8]).reshape(4, 2), ("data", "model"))
    rng = np.random.RandomState(4)
    b, fin, fh = 8, 10, 6
    net = _mlp()
    args_np = {
        "data": rng.randn(b, fin).astype(np.float32),
        "softmax_label": (np.arange(b) % 4).astype(np.float32),
        "fc1_weight": rng.randn(16, fin).astype(np.float32) * 0.3,
        "fc1_bias": np.zeros(16, np.float32),
        "fc2_weight": rng.randn(4, 16).astype(np.float32) * 0.3,
        "fc2_bias": np.zeros(4, np.float32),
    }
    del fh

    results = {}
    for tag in ("tp", "oracle"):
        ex = net.bind(mx.cpu(),
                      {k: nd.array(v) for k, v in args_np.items()},
                      args_grad={k: nd.zeros(v.shape)
                                 for k, v in args_np.items()
                                 if k not in ("data", "softmax_label")})
        if tag == "tp":
            ex.set_shardings(mesh, {"fc1_weight": P("model", None),
                                    "fc1_bias": P("model"),
                                    "data": P("data", None),
                                    "softmax_label": P("data")})
            shards = ex.arg_dict["fc1_weight"]._data.addressable_shards
            assert len({s.device for s in shards}) == 8
            # 'model' axis split: each shard holds half the rows
            assert shards[0].data.shape == (8, fin)
        ex.forward_backward()
        results[tag] = ({k: v.asnumpy() for k, v in ex.grad_dict.items()},
                        ex.outputs[0].asnumpy())
        if tag == "tp":
            # a fresh batch through forward(**kwargs) keeps the data spec
            ex.forward(is_train=False,
                       data=rng.randn(b, fin).astype(np.float32))
            dsh = ex.arg_dict["data"]._data.sharding
            assert dsh.spec == P("data", None)

    for k in results["oracle"][0]:
        assert_almost_equal(results["tp"][0][k], results["oracle"][0][k],
                            rtol=1e-5, atol=1e-6)
    assert_almost_equal(results["tp"][1], results["oracle"][1],
                        rtol=1e-5, atol=1e-6)

"""Data-parallel execution over the 8-virtual-device CPU mesh
(reference: tests/python/unittest/test_multi_device_exec.py +
executor_group slicing semantics; here the mesh replaces per-device
executors and XLA inserts the gradient reduction)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def _devices():
    import jax
    return jax.devices()


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def test_eight_virtual_devices_present():
    assert len(_devices()) >= 8, \
        "conftest must force 8 virtual CPU devices"


def test_dp_forward_matches_single_device():
    n_dev = 8
    ctxs = [mx.cpu(i) for i in range(n_dev)]
    X = np.random.randn(16, 10).astype(np.float32)
    y = np.zeros(16, np.float32)

    mod1 = mx.mod.Module(_mlp(), label_names=("softmax_label",),
                         context=mx.cpu(0))
    mod1.bind(data_shapes=[("data", (16, 10))],
              label_shapes=[("softmax_label", (16,))])
    mod1.init_params(mx.init.Xavier(rnd_type="uniform", magnitude=2.0))

    modN = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    modN.bind(data_shapes=[("data", (16, 10))],
              label_shapes=[("softmax_label", (16,))])
    arg, aux = mod1.get_params()
    modN.set_params(arg, aux)

    batch = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(y)])
    mod1.forward(batch, is_train=False)
    modN.forward(batch, is_train=False)
    assert_almost_equal(mod1.get_outputs()[0], modN.get_outputs()[0],
                        rtol=1e-5, atol=1e-6)


def test_dp_gradients_match_single_device():
    ctxs = [mx.cpu(i) for i in range(8)]
    X = np.random.randn(16, 10).astype(np.float32)
    y = (np.arange(16) % 4).astype(np.float32)

    def run(mod):
        mod.bind(data_shapes=[("data", (16, 10))],
                 label_shapes=[("softmax_label", (16,))], for_training=True)
        mod.init_params(mx.init.Uniform(0.1))
        return mod

    mod1 = run(mx.mod.Module(_mlp(), label_names=("softmax_label",),
                             context=mx.cpu(0)))
    modN = run(mx.mod.Module(_mlp(), label_names=("softmax_label",),
                             context=ctxs))
    arg, aux = mod1.get_params()
    modN.set_params(arg, aux)

    batch = mx.io.DataBatch(data=[nd.array(X)], label=[nd.array(y)])
    for mod in (mod1, modN):
        mod.forward(batch, is_train=True)
        mod.backward()
    g1 = mod1._exec_group.execs[0].grad_dict
    gN = modN._exec_group.execs[0].grad_dict
    for name in g1:
        assert_almost_equal(g1[name], gN[name], rtol=1e-4, atol=1e-5,
                            names=("single[%s]" % name, "mesh[%s]" % name))


def test_dp_batch_is_sharded_params_replicated():
    ctxs = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    mod.bind(data_shapes=[("data", (32, 10))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params()
    exe = mod._exec_group.execs[0]
    data_sh = exe.arg_dict["data"]._data.sharding
    w_sh = exe.arg_dict["fc1_weight"]._data.sharding
    assert not data_sh.is_fully_replicated
    assert w_sh.is_fully_replicated


def test_dp_fit_converges():
    rng = np.random.RandomState(3)
    X = rng.randn(256, 10).astype(np.float32)
    w = rng.randn(10, 4).astype(np.float32)
    y = np.argmax(X @ w, axis=1).astype(np.float32)
    ctxs = [mx.cpu(i) for i in range(8)]
    train = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    mod.fit(train, num_epoch=25, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            kvstore="device")
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=64),
                      mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc >= 0.9, "DP fit under-converged: %f" % acc


def test_indivisible_batch_raises():
    ctxs = [mx.cpu(i) for i in range(3)]
    mod = mx.mod.Module(_mlp(), label_names=("softmax_label",), context=ctxs)
    with pytest.raises(mx.MXNetError):
        mod.bind(data_shapes=[("data", (16, 10))],
                 label_shapes=[("softmax_label", (16,))])

"""C prediction ABI round trip: train -> checkpoint -> drive the graph
through libmxtpu_capi.so via ctypes, exactly as a C program (or another
language binding) would (reference: include/mxnet/c_predict_api.h and
src/c_api/c_predict_api.cc:41-280)."""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
SO = os.path.join(ROOT, "mxnet_tpu", "libmxtpu_capi.so")


def _build_lib():
    if not os.path.exists(SO):
        subprocess.run(["make", "capi"], cwd=os.path.join(ROOT, "src"),
                       check=True, capture_output=True)
    lib = ctypes.CDLL(SO)
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    return lib


def _train_checkpoint(tmp_path):
    np.random.seed(3)
    X = np.random.randn(60, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 3)
    return prefix, X


def test_c_predict_roundtrip(tmp_path):
    lib = _build_lib()
    prefix, X = _train_checkpoint(tmp_path)
    with open(prefix + "-symbol.json") as f:
        sym_json = f.read().encode()
    with open(prefix + "-0003.params", "rb") as f:
        params = f.read()

    batch = X[:10]
    keys = (ctypes.c_char_p * 2)(b"data", b"softmax_label")
    indptr = (ctypes.c_uint32 * 3)(0, 2, 3)
    shapes = (ctypes.c_uint32 * 3)(10, 6, 10)
    handle = ctypes.c_void_p()
    rc = lib.MXTPUPredCreate(sym_json, params, len(params), 1, 0,
                             2, keys, indptr, shapes, ctypes.byref(handle))
    assert rc == 0, lib.MXTPUGetLastError().decode()

    data = np.ascontiguousarray(batch, np.float32)
    rc = lib.MXTPUPredSetInput(
        handle, b"data", data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        data.size)
    assert rc == 0, lib.MXTPUGetLastError().decode()
    assert lib.MXTPUPredForward(handle) == 0

    sdata = ctypes.POINTER(ctypes.c_uint32)()
    ndim = ctypes.c_uint32()
    rc = lib.MXTPUPredGetOutputShape(handle, 0, ctypes.byref(sdata),
                                     ctypes.byref(ndim))
    assert rc == 0
    shape = tuple(sdata[i] for i in range(ndim.value))
    assert shape == (10, 2)

    out = np.zeros(shape, np.float32)
    rc = lib.MXTPUPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size)
    assert rc == 0, lib.MXTPUGetLastError().decode()
    np.testing.assert_allclose(out.sum(axis=1), np.ones(10), rtol=1e-5)

    # must equal the Python Predictor on the same checkpoint
    pred = mx.Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                        {"data": (10, 6), "softmax_label": (10,)})
    want = pred.forward(data=batch)[0].asnumpy()
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    # reshape shares weights and serves a different batch size
    indptr4 = (ctypes.c_uint32 * 3)(0, 2, 3)
    shapes4 = (ctypes.c_uint32 * 3)(4, 6, 4)
    h4 = ctypes.c_void_p()
    rc = lib.MXTPUPredReshape(2, keys, indptr4, shapes4, handle,
                              ctypes.byref(h4))
    assert rc == 0, lib.MXTPUGetLastError().decode()
    d4 = np.ascontiguousarray(batch[:4], np.float32)
    assert lib.MXTPUPredSetInput(
        h4, b"data", d4.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        d4.size) == 0
    assert lib.MXTPUPredForward(h4) == 0
    out4 = np.zeros((4, 2), np.float32)
    assert lib.MXTPUPredGetOutput(
        h4, 0, out4.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out4.size) == 0
    np.testing.assert_allclose(out4, want[:4], rtol=1e-4, atol=1e-6)

    assert lib.MXTPUPredFree(h4) == 0
    assert lib.MXTPUPredFree(handle) == 0


def test_c_predict_error_reporting(tmp_path):
    lib = _build_lib()
    keys = (ctypes.c_char_p * 1)(b"data",)
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shapes = (ctypes.c_uint32 * 2)(4, 4)
    handle = ctypes.c_void_p()
    rc = lib.MXTPUPredCreate(b"{not json", None, 0, 1, 0, 1, keys, indptr,
                             shapes, ctypes.byref(handle))
    assert rc == -1
    assert len(lib.MXTPUGetLastError()) > 0


def test_standalone_c_embedder(tmp_path):
    """Compile and run a real C program against the ABI: the process starts
    with no Python; the library embeds the interpreter itself."""
    lib = _build_lib()  # ensure the .so exists
    del lib
    prefix, X = _train_checkpoint(tmp_path)
    exe = str(tmp_path / "demo")
    import sysconfig

    libdir = sysconfig.get_config_var("LIBDIR")
    res = subprocess.run(
        ["gcc", "-O2", os.path.join(ROOT, "examples", "c_predict", "demo.c"),
         "-I", os.path.join(ROOT, "include"),
         "-L", os.path.join(ROOT, "mxnet_tpu"), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.join(ROOT, "mxnet_tpu"),
         "-Wl,-rpath," + libdir, "-o", exe],
        capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": ROOT}
    run = subprocess.run([exe, str(tmp_path / "m"), "3", "10", "6"],
                         capture_output=True, text=True, timeout=240,
                         env=env)
    assert run.returncode == 0, (run.stdout, run.stderr)
    row = [float(v) for v in run.stdout.strip().split(",")]
    assert len(row) == 2 and abs(sum(row) - 1.0) < 1e-4  # softmax row

"""C prediction ABI round trip: train -> checkpoint -> drive the graph
through libmxtpu_capi.so via ctypes, exactly as a C program (or another
language binding) would (reference: include/mxnet/c_predict_api.h and
src/c_api/c_predict_api.cc:41-280)."""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
SO = os.path.join(ROOT, "mxnet_tpu", "libmxtpu_capi.so")


def _build_lib():
    if not os.path.exists(SO):
        subprocess.run(["make", "capi"], cwd=os.path.join(ROOT, "src"),
                       check=True, capture_output=True)
    lib = ctypes.CDLL(SO)
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    return lib


def _compile_and_run(src, run_args, compiler="gcc", timeout=240,
                     std=None, check_output=True):
    """Compile an examples/ program against libmxtpu_capi.so and run it
    (one copy of the link/rpath/env boilerplate for all embedder tests)."""
    import sysconfig
    import tempfile

    _build_lib()
    libdir = sysconfig.get_config_var("LIBDIR")
    with tempfile.TemporaryDirectory() as d:
        exe = os.path.join(d, "prog")
        cmd = [compiler, "-O2"] + (["-std=" + std] if std else []) + [
            os.path.join(ROOT, src),
            "-I", os.path.join(ROOT, "include"),
            "-L", os.path.join(ROOT, "mxnet_tpu"), "-lmxtpu_capi",
            "-Wl,-rpath," + os.path.join(ROOT, "mxnet_tpu"),
            "-Wl,-rpath," + libdir, "-o", exe]
        res = subprocess.run(cmd, capture_output=True, text=True)
        assert res.returncode == 0, res.stderr[-1200:]
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT}
        run = subprocess.run([exe] + run_args, capture_output=True,
                             text=True, timeout=timeout, env=env)
    if check_output:
        assert run.returncode == 0, (run.stdout, run.stderr)
    return run


def _train_checkpoint(tmp_path):
    np.random.seed(3)
    X = np.random.randn(60, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 3)
    return prefix, X


def test_c_predict_roundtrip(tmp_path):
    lib = _build_lib()
    prefix, X = _train_checkpoint(tmp_path)
    with open(prefix + "-symbol.json") as f:
        sym_json = f.read().encode()
    with open(prefix + "-0003.params", "rb") as f:
        params = f.read()

    batch = X[:10]
    keys = (ctypes.c_char_p * 2)(b"data", b"softmax_label")
    indptr = (ctypes.c_uint32 * 3)(0, 2, 3)
    shapes = (ctypes.c_uint32 * 3)(10, 6, 10)
    handle = ctypes.c_void_p()
    rc = lib.MXTPUPredCreate(sym_json, params, len(params), 1, 0,
                             2, keys, indptr, shapes, ctypes.byref(handle))
    assert rc == 0, lib.MXTPUGetLastError().decode()

    data = np.ascontiguousarray(batch, np.float32)
    rc = lib.MXTPUPredSetInput(
        handle, b"data", data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        data.size)
    assert rc == 0, lib.MXTPUGetLastError().decode()
    assert lib.MXTPUPredForward(handle) == 0

    sdata = ctypes.POINTER(ctypes.c_uint32)()
    ndim = ctypes.c_uint32()
    rc = lib.MXTPUPredGetOutputShape(handle, 0, ctypes.byref(sdata),
                                     ctypes.byref(ndim))
    assert rc == 0
    shape = tuple(sdata[i] for i in range(ndim.value))
    assert shape == (10, 2)

    out = np.zeros(shape, np.float32)
    rc = lib.MXTPUPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size)
    assert rc == 0, lib.MXTPUGetLastError().decode()
    np.testing.assert_allclose(out.sum(axis=1), np.ones(10), rtol=1e-5)

    # must equal the Python Predictor on the same checkpoint
    pred = mx.Predictor(prefix + "-symbol.json", prefix + "-0003.params",
                        {"data": (10, 6), "softmax_label": (10,)})
    want = pred.forward(data=batch)[0].asnumpy()
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    # reshape shares weights and serves a different batch size
    indptr4 = (ctypes.c_uint32 * 3)(0, 2, 3)
    shapes4 = (ctypes.c_uint32 * 3)(4, 6, 4)
    h4 = ctypes.c_void_p()
    rc = lib.MXTPUPredReshape(2, keys, indptr4, shapes4, handle,
                              ctypes.byref(h4))
    assert rc == 0, lib.MXTPUGetLastError().decode()
    d4 = np.ascontiguousarray(batch[:4], np.float32)
    assert lib.MXTPUPredSetInput(
        h4, b"data", d4.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        d4.size) == 0
    assert lib.MXTPUPredForward(h4) == 0
    out4 = np.zeros((4, 2), np.float32)
    assert lib.MXTPUPredGetOutput(
        h4, 0, out4.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out4.size) == 0
    np.testing.assert_allclose(out4, want[:4], rtol=1e-4, atol=1e-6)

    assert lib.MXTPUPredFree(h4) == 0
    assert lib.MXTPUPredFree(handle) == 0


def test_c_predict_error_reporting(tmp_path):
    lib = _build_lib()
    keys = (ctypes.c_char_p * 1)(b"data",)
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shapes = (ctypes.c_uint32 * 2)(4, 4)
    handle = ctypes.c_void_p()
    rc = lib.MXTPUPredCreate(b"{not json", None, 0, 1, 0, 1, keys, indptr,
                             shapes, ctypes.byref(handle))
    assert rc == -1
    assert len(lib.MXTPUGetLastError()) > 0


def test_standalone_c_embedder(tmp_path):
    """Compile and run a real C program against the ABI: the process starts
    with no Python; the library embeds the interpreter itself."""
    prefix, X = _train_checkpoint(tmp_path)
    run = _compile_and_run(os.path.join("examples", "c_predict", "demo.c"),
                           [str(tmp_path / "m"), "3", "10", "6"])
    row = [float(v) for v in run.stdout.strip().split(",")]
    assert len(row) == 2 and abs(sum(row) - 1.0) < 1e-4  # softmax row


def test_core_c_api_ndarray_and_invoke(tmp_path):
    """Core C ABI (include/mxtpu/c_api.h): NDArray CRUD, imperative op
    invoke with string attrs, .params save/load, op-name listing —
    the reference c_api.cc NDArray surface driven via ctypes."""
    lib = _build_lib()

    # create a (2, 3) f32 array and fill it
    shape = (ctypes.c_uint32 * 2)(2, 3)
    h = ctypes.c_void_p()
    assert lib.MXTPUNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h)) == 0
    src = np.arange(6, dtype=np.float32).reshape(2, 3)
    assert lib.MXTPUNDArraySyncCopyFromCPU(
        h, src.ctypes.data_as(ctypes.c_void_p), src.nbytes) == 0

    # shape / dtype readback
    ndim = ctypes.c_uint32()
    sdata = ctypes.POINTER(ctypes.c_uint32)()
    assert lib.MXTPUNDArrayGetShape(h, ctypes.byref(ndim),
                                    ctypes.byref(sdata)) == 0
    assert [sdata[i] for i in range(ndim.value)] == [2, 3]
    dt = ctypes.c_int()
    assert lib.MXTPUNDArrayGetDType(h, ctypes.byref(dt)) == 0
    assert dt.value == 0  # float32 flag

    # imperative invoke with a string attr: sum over axis 1
    n_out = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    keys = (ctypes.c_char_p * 1)(b"axis")
    vals = (ctypes.c_char_p * 1)(b"1")
    ins = (ctypes.c_void_p * 1)(h)
    assert lib.MXTPUImperativeInvoke(
        b"sum", 1, ins, ctypes.byref(n_out), ctypes.byref(outs),
        1, keys, vals) == 0, lib.MXTPUGetLastError()
    assert n_out.value == 1
    sum_h = ctypes.c_void_p(outs[0])
    lib.MXTPUFreeHandleArray(outs)
    out = np.zeros(2, np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        sum_h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes) == 0
    np.testing.assert_allclose(out, src.sum(axis=1))

    # save named, load back, values survive
    fname = str(tmp_path / "blob.params").encode()
    names = (ctypes.c_char_p * 1)(b"w",)
    assert lib.MXTPUNDArraySave(fname, 1, ins, names) == 0
    n_arr = ctypes.c_uint32()
    arrs = ctypes.POINTER(ctypes.c_void_p)()
    n_names = ctypes.c_uint32()
    out_names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUNDArrayLoad(fname, ctypes.byref(n_arr),
                                ctypes.byref(arrs), ctypes.byref(n_names),
                                ctypes.byref(out_names)) == 0
    assert n_arr.value == 1 and n_names.value == 1
    assert out_names[0] == b"w"
    loaded_h = ctypes.c_void_p(arrs[0])
    lib.MXTPUFreeHandleArray(arrs)
    back = np.zeros((2, 3), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        loaded_h, back.ctypes.data_as(ctypes.c_void_p), back.nbytes) == 0
    np.testing.assert_allclose(back, src)

    # op registry listing includes the core names
    n_ops = ctypes.c_uint32()
    op_names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUListAllOpNames(ctypes.byref(n_ops),
                                   ctypes.byref(op_names)) == 0
    all_ops = {op_names[i] for i in range(n_ops.value)}
    assert {b"Convolution", b"FullyConnected", b"sum",
            b"_contrib_FlashAttention"} <= all_ops

    # error path: bad op name reports through MXTPUGetLastError
    assert lib.MXTPUImperativeInvoke(b"no_such_op", 1, ins,
                                     ctypes.byref(n_out), ctypes.byref(outs),
                                     0, None, None) == -1
    assert b"no_such_op" in lib.MXTPUGetLastError()
    # per the header contract, invoke/load output handles are caller-owned
    lib.MXTPUNDArrayFree(sum_h)
    lib.MXTPUNDArrayFree(loaded_h)
    lib.MXTPUNDArrayFree(h)


def test_c_symbol_executor_surface(tmp_path):
    """Build a graph from JSON, infer shapes, bind, and run forward +
    backward entirely through the C ABI; outputs and gradients must match
    the Python executor on the same weights (reference surface:
    c_api_symbolic.cc:54-545, c_api_executor.cc:11-157)."""
    lib = _build_lib()

    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    json_bytes = net.tojson().encode()

    # --- symbol: create from JSON, list names, JSON round trip ---------
    sym = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateFromJSON(json_bytes, ctypes.byref(sym)) == 0, \
        lib.MXTPUGetLastError().decode()
    n = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUSymbolListArguments(sym, ctypes.byref(n),
                                        ctypes.byref(names)) == 0
    arg_names = [names[i].decode() for i in range(n.value)]
    assert arg_names == net.list_arguments()
    assert lib.MXTPUSymbolListOutputs(sym, ctypes.byref(n),
                                      ctypes.byref(names)) == 0
    assert [names[i].decode() for i in range(n.value)] == net.list_outputs()
    out_json = ctypes.c_char_p()
    assert lib.MXTPUSymbolSaveToJSON(sym, ctypes.byref(out_json)) == 0
    assert mx.sym.load_json(out_json.value.decode()).list_arguments() \
        == arg_names

    # --- infer shape (CSR input, the reference signature) --------------
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    sdata = (ctypes.c_uint32 * 2)(5, 7)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u32pp = ctypes.POINTER(u32p)
    sizes = [ctypes.c_uint32() for _ in range(3)]
    ndims = [u32p() for _ in range(3)]
    datas = [u32pp() for _ in range(3)]
    complete = ctypes.c_int()
    assert lib.MXTPUSymbolInferShape(
        sym, 1, keys, indptr, sdata,
        ctypes.byref(sizes[0]), ctypes.byref(ndims[0]), ctypes.byref(datas[0]),
        ctypes.byref(sizes[1]), ctypes.byref(ndims[1]), ctypes.byref(datas[1]),
        ctypes.byref(sizes[2]), ctypes.byref(ndims[2]), ctypes.byref(datas[2]),
        ctypes.byref(complete)) == 0, lib.MXTPUGetLastError().decode()
    assert complete.value == 1
    ref_args, ref_outs, _ = net.infer_shape(data=(5, 7))
    got_args = [tuple(datas[0][i][j] for j in range(ndims[0][i]))
                for i in range(sizes[0].value)]
    assert got_args == [tuple(s) for s in ref_args]
    got_outs = [tuple(datas[1][i][j] for j in range(ndims[1][i]))
                for i in range(sizes[1].value)]
    assert got_outs == [tuple(s) for s in ref_outs]

    # --- bind + forward + backward -------------------------------------
    rng = np.random.RandomState(7)
    arg_arrays = [rng.randn(*s).astype(np.float32) * 0.3 for s in ref_args]

    def make_nd(a):
        h = ctypes.c_void_p()
        shp = (ctypes.c_uint32 * a.ndim)(*a.shape)
        assert lib.MXTPUNDArrayCreate(shp, a.ndim, 1, 0, 0,
                                      ctypes.byref(h)) == 0
        assert lib.MXTPUNDArraySyncCopyFromCPU(
            h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes) == 0
        return h

    arg_h = [make_nd(a) for a in arg_arrays]
    grad_h = [make_nd(np.zeros_like(a)) for a in arg_arrays]
    args_c = (ctypes.c_void_p * len(arg_h))(*[h.value for h in arg_h])
    grads_c = (ctypes.c_void_p * len(grad_h))(*[h.value for h in grad_h])
    reqs = (ctypes.c_uint32 * len(arg_h))(*([1] * len(arg_h)))
    ex = ctypes.c_void_p()
    assert lib.MXTPUExecutorBind(sym, 1, 0, len(arg_h), args_c, grads_c,
                                 reqs, 0, None, ctypes.byref(ex)) == 0, \
        lib.MXTPUGetLastError().decode()
    assert lib.MXTPUExecutorForward(ex, 1) == 0

    n_out = ctypes.c_uint32()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXTPUExecutorOutputs(ex, ctypes.byref(n_out),
                                    ctypes.byref(outs)) == 0
    assert n_out.value == 1
    got = np.zeros((5, 3), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        ctypes.c_void_p(outs[0]), got.ctypes.data_as(ctypes.c_void_p),
        got.nbytes) == 0

    # Python oracle on the same weights
    py_ex = net.bind(mx.cpu(),
                     {k: mx.nd.array(a)
                      for k, a in zip(arg_names, arg_arrays)},
                     args_grad={k: mx.nd.zeros(a.shape) for k, a in
                                zip(arg_names, arg_arrays)},
                     grad_req="write")
    py_ex.forward(is_train=True)
    np.testing.assert_allclose(got, py_ex.outputs[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)

    # backward with explicit head gradients, grads must match in place
    head = rng.randn(5, 3).astype(np.float32)
    head_h = make_nd(head)
    heads_c = (ctypes.c_void_p * 1)(head_h.value)
    assert lib.MXTPUExecutorBackward(ex, 1, heads_c) == 0, \
        lib.MXTPUGetLastError().decode()
    py_ex.backward(out_grads=[mx.nd.array(head)])
    for name, gh, a in zip(arg_names, grad_h, arg_arrays):
        g = np.zeros_like(a)
        assert lib.MXTPUNDArraySyncCopyToCPU(
            gh, g.ctypes.data_as(ctypes.c_void_p), g.nbytes) == 0
        np.testing.assert_allclose(g, py_ex.grad_dict[name].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=name)

    # incomplete shapes report complete=0, not an error
    assert lib.MXTPUSymbolInferShape(
        sym, 0, None, (ctypes.c_uint32 * 1)(0), None,
        ctypes.byref(sizes[0]), ctypes.byref(ndims[0]), ctypes.byref(datas[0]),
        ctypes.byref(sizes[1]), ctypes.byref(ndims[1]), ctypes.byref(datas[1]),
        ctypes.byref(sizes[2]), ctypes.byref(ndims[2]), ctypes.byref(datas[2]),
        ctypes.byref(complete)) == 0
    assert complete.value == 0

    # header ownership contract: each output handle, then the array
    for i in range(n_out.value):
        lib.MXTPUNDArrayFree(ctypes.c_void_p(outs[i]))
    lib.MXTPUFreeHandleArray(outs)
    for h in arg_h + grad_h + [head_h]:
        lib.MXTPUNDArrayFree(h)
    lib.MXTPUExecutorFree(ex)
    lib.MXTPUSymbolFree(sym)


def test_standalone_c_symbol_executor_demo(tmp_path):
    """demo_symbol.c: a no-Python C program builds the graph from JSON,
    binds checkpoint weights via the symbol/executor ABI and classifies;
    its output must match the Python predictor on the same batch."""
    prefix, X = _train_checkpoint(tmp_path)
    run = _compile_and_run(
        os.path.join("examples", "c_predict", "demo_symbol.c"),
        [str(tmp_path / "m"), "3", "10", "6"])
    row = np.array([float(v) for v in run.stdout.strip().split(",")])
    assert row.shape == (2,) and abs(row.sum() - 1.0) < 1e-4

    # Python oracle: same deterministic batch the C program generates
    x = ((np.arange(60) % 7) - 3).astype(np.float32).reshape(10, 6) * 0.25
    pred = mx.Predictor(str(tmp_path / "m-symbol.json"),
                        str(tmp_path / "m-0003.params"),
                        {"data": (10, 6), "softmax_label": (10,)})
    want = pred.forward(data=x)[0].asnumpy()[0]
    np.testing.assert_allclose(row, want, rtol=1e-4, atol=1e-6)


def test_c_dataiter_surface(tmp_path):
    """Drive the file-backed input pipeline from C (reference
    c_api.cc:446-543): create a CSVIter with string attrs, iterate
    batches, read data/label/pad, rewind — values must match the Python
    iterator on the same files."""
    lib = _build_lib()

    rng = np.random.RandomState(11)
    data = rng.randn(10, 6).astype(np.float32)
    label = (np.arange(10) % 3).astype(np.float32).reshape(10, 1)
    data_csv = str(tmp_path / "d.csv")
    label_csv = str(tmp_path / "l.csv")
    np.savetxt(data_csv, data, delimiter=",")
    np.savetxt(label_csv, label, delimiter=",")

    n = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUListDataIters(ctypes.byref(n), ctypes.byref(names)) == 0
    iter_names = {names[i] for i in range(n.value)}
    assert b"CSVIter" in iter_names and b"MNISTIter" in iter_names

    keys = (ctypes.c_char_p * 5)(b"data_csv", b"label_csv", b"data_shape",
                                 b"label_shape", b"batch_size")
    vals = (ctypes.c_char_p * 5)(data_csv.encode(), label_csv.encode(),
                                 b"(6,)", b"(1,)", b"4")
    it = ctypes.c_void_p()
    assert lib.MXTPUDataIterCreate(b"CSVIter", 5, keys, vals,
                                   ctypes.byref(it)) == 0, \
        lib.MXTPUGetLastError().decode()

    py_it = mx.io.CSVIter(data_csv=data_csv, label_csv=label_csv,
                          data_shape=(6,), label_shape=(1,), batch_size=4)

    def drain():
        got = []
        more = ctypes.c_int()
        pad = ctypes.c_int()
        while True:
            assert lib.MXTPUDataIterNext(it, ctypes.byref(more)) == 0
            if not more.value:
                break
            dh = ctypes.c_void_p()
            lh = ctypes.c_void_p()
            assert lib.MXTPUDataIterGetData(it, ctypes.byref(dh)) == 0
            assert lib.MXTPUDataIterGetLabel(it, ctypes.byref(lh)) == 0
            assert lib.MXTPUDataIterGetPadNum(it, ctypes.byref(pad)) == 0
            d = np.zeros((4, 6), np.float32)
            l = np.zeros((4, 1), np.float32)
            assert lib.MXTPUNDArraySyncCopyToCPU(
                dh, d.ctypes.data_as(ctypes.c_void_p), d.nbytes) == 0
            assert lib.MXTPUNDArraySyncCopyToCPU(
                lh, l.ctypes.data_as(ctypes.c_void_p), l.nbytes) == 0
            got.append((d, l, pad.value))
            lib.MXTPUNDArrayFree(dh)
            lib.MXTPUNDArrayFree(lh)
        return got

    got = drain()
    want = [(b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad)
            for b in py_it]
    assert len(got) == len(want) == 3  # 10 rows / batch 4, padded tail
    for (gd, gl, gp), (wd, wl, wp) in zip(got, want):
        np.testing.assert_allclose(gd, wd, rtol=1e-6)
        np.testing.assert_allclose(gl, wl, rtol=1e-6)
        assert gp == wp
    assert got[-1][2] == 2  # 12 - 10 padded rows

    # rewind and confirm the first batch repeats
    assert lib.MXTPUDataIterBeforeFirst(it) == 0
    again = drain()
    np.testing.assert_allclose(again[0][0], got[0][0], rtol=1e-6)

    # error path: unknown iterator name
    bad = ctypes.c_void_p()
    assert lib.MXTPUDataIterCreate(b"NoSuchIter", 0, None, None,
                                   ctypes.byref(bad)) == -1
    assert b"NoSuchIter" in lib.MXTPUGetLastError()
    lib.MXTPUDataIterFree(it)


def test_c_kvstore_surface():
    """KVStore from C (reference c_api.cc:544-700): create local store,
    init/push/pull with int keys, rank/size/type getters, barrier no-op
    on the local store."""
    lib = _build_lib()
    lib.MXTPUKVStoreGetType.restype = ctypes.c_int

    kv = ctypes.c_void_p()
    assert lib.MXTPUKVStoreCreate(b"local", ctypes.byref(kv)) == 0, \
        lib.MXTPUGetLastError().decode()

    tp = ctypes.c_char_p()
    assert lib.MXTPUKVStoreGetType(kv, ctypes.byref(tp)) == 0
    assert tp.value == b"local"
    rank = ctypes.c_int()
    size = ctypes.c_int()
    assert lib.MXTPUKVStoreGetRank(kv, ctypes.byref(rank)) == 0
    assert lib.MXTPUKVStoreGetGroupSize(kv, ctypes.byref(size)) == 0
    assert rank.value == 0 and size.value == 1

    def make_nd(a):
        h = ctypes.c_void_p()
        shp = (ctypes.c_uint32 * a.ndim)(*a.shape)
        assert lib.MXTPUNDArrayCreate(shp, a.ndim, 1, 0, 0,
                                      ctypes.byref(h)) == 0
        assert lib.MXTPUNDArraySyncCopyFromCPU(
            h, a.ctypes.data_as(ctypes.c_void_p), a.nbytes) == 0
        return h

    w = np.arange(6, dtype=np.float32).reshape(2, 3)
    g = np.ones((2, 3), np.float32)
    wh, gh = make_nd(w), make_nd(g)
    keys = (ctypes.c_int * 1)(3)
    vals_w = (ctypes.c_void_p * 1)(wh.value)
    vals_g = (ctypes.c_void_p * 1)(gh.value)
    assert lib.MXTPUKVStoreInit(kv, 1, keys, vals_w) == 0
    assert lib.MXTPUKVStorePush(kv, 1, keys, vals_g) == 0
    assert lib.MXTPUKVStorePush(kv, 1, keys, vals_g) == 0
    outh = make_nd(np.zeros((2, 3), np.float32))
    vals_o = (ctypes.c_void_p * 1)(outh.value)
    assert lib.MXTPUKVStorePull(kv, 1, keys, vals_o) == 0
    got = np.zeros((2, 3), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        outh, got.ctypes.data_as(ctypes.c_void_p), got.nbytes) == 0
    # local-store semantics (kvstore_local.h:50): each push REPLACES the
    # store with that push's merged value; pull returns the last merge
    np.testing.assert_allclose(got, g)
    assert lib.MXTPUKVStoreBarrier(kv) == 0
    for h in (wh, gh, outh):
        lib.MXTPUNDArrayFree(h)
    assert lib.MXTPUKVStoreFree(kv) == 0


def test_c_graph_building_and_views():
    """Round-5 breadth: build a graph from C with CreateVariable/
    CreateAtomicSymbol/Compose (no JSON), bind, forward; NDArray
    slice/reshape/context/copy; executor reshape; version/seed
    (reference c_api_symbolic.cc:54-220, MXExecutorReshape)."""
    lib = _build_lib()
    err = lambda: lib.MXTPUGetLastError().decode()

    # version
    out = ctypes.c_char_p()
    assert lib.MXTPUGetVersion(ctypes.byref(out)) == 0, err()
    assert out.value.decode() == mx.__version__

    assert lib.MXTPURandomSeed(7) == 0, err()

    # data variable + FullyConnected(num_hidden=3) composed from C
    data = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateVariable(
        b"data", ctypes.byref(data)) == 0, err()
    fc = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 2)(b"num_hidden", b"no_bias")
    vals = (ctypes.c_char_p * 2)(b"3", b"True")
    assert lib.MXTPUSymbolCreateAtomicSymbol(
        b"FullyConnected", 2, keys, vals, ctypes.byref(fc)) == 0, err()
    ckeys = (ctypes.c_char_p * 1)(b"data")
    args = (ctypes.c_void_p * 1)(data)
    assert lib.MXTPUSymbolCompose(fc, b"fc0", 1, ckeys, args) == 0, err()

    # the composed symbol lists the generated weight argument
    n = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUSymbolListArguments(
        fc, ctypes.byref(n), ctypes.byref(names)) == 0, err()
    arg_names = [names[i].decode() for i in range(n.value)]
    assert arg_names == ["data", "fc0_weight"], arg_names

    # bind with C-created NDArrays and forward
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 6).astype(np.float32)
    wv = rng.randn(3, 6).astype(np.float32)

    def c_array(v):
        h = ctypes.c_void_p()
        shp = (ctypes.c_uint32 * v.ndim)(*v.shape)
        assert lib.MXTPUNDArrayCreate(shp, v.ndim, 1, 0, 0,
                                      ctypes.byref(h)) == 0, err()
        assert lib.MXTPUNDArraySyncCopyFromCPU(
            h, v.ctypes.data_as(ctypes.c_void_p), v.nbytes) == 0, err()
        return h

    hx, hw = c_array(xv), c_array(wv)
    arg_handles = (ctypes.c_void_p * 2)(hx, hw)
    ex = ctypes.c_void_p()
    assert lib.MXTPUExecutorBind(fc, 1, 0, 2, arg_handles, None, None,
                                 0, None, ctypes.byref(ex)) == 0, err()
    assert lib.MXTPUExecutorForward(ex, 0) == 0, err()
    n_out = ctypes.c_uint32()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXTPUExecutorOutputs(
        ex, ctypes.byref(n_out), ctypes.byref(outs)) == 0, err()
    got = np.zeros((4, 3), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        ctypes.c_void_p(outs[0]),
        got.ctypes.data_as(ctypes.c_void_p), got.nbytes) == 0
    np.testing.assert_allclose(got, xv @ wv.T, rtol=1e-4, atol=1e-5)

    # views: slice rows 1:3, reshape to (3, 4), context
    hs = ctypes.c_void_p()
    assert lib.MXTPUNDArraySlice(hx, 1, 3, ctypes.byref(hs)) == 0, err()
    sl = np.zeros((2, 6), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        hs, sl.ctypes.data_as(ctypes.c_void_p), sl.nbytes) == 0
    np.testing.assert_array_equal(sl, xv[1:3])
    hr = ctypes.c_void_p()
    dims = (ctypes.c_int * 2)(8, 3)
    assert lib.MXTPUNDArrayReshape(hx, 2, dims, ctypes.byref(hr)) == 0, err()
    rs = np.zeros((8, 3), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        hr, rs.ctypes.data_as(ctypes.c_void_p), rs.nbytes) == 0
    np.testing.assert_array_equal(rs, xv.reshape(8, 3))
    dt, di = ctypes.c_int(), ctypes.c_int()
    assert lib.MXTPUNDArrayGetContext(
        hx, ctypes.byref(dt), ctypes.byref(di)) == 0, err()
    assert dt.value == 1  # cpu

    # copy: hx -> fresh buffer
    hc = c_array(np.zeros_like(xv))
    assert lib.MXTPUNDArrayCopyFromTo(hx, hc) == 0, err()
    cp = np.zeros_like(xv)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        hc, cp.ctypes.data_as(ctypes.c_void_p), cp.nbytes) == 0
    np.testing.assert_array_equal(cp, xv)

    # executor reshape to batch 2 and forward again
    rkeys = (ctypes.c_char_p * 1)(b"data")
    ndims = (ctypes.c_uint32 * 1)(2)
    shape0 = (ctypes.c_uint32 * 2)(2, 6)
    shape_ptrs = (ctypes.POINTER(ctypes.c_uint32) * 1)(shape0)
    ex2 = ctypes.c_void_p()
    assert lib.MXTPUExecutorReshape(ex, 1, rkeys, ndims, shape_ptrs,
                                    ctypes.byref(ex2)) == 0, err()
    assert lib.MXTPUExecutorForward(ex2, 0) == 0, err()

    # compose error surfaces through GetLastError
    bad = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateAtomicSymbol(
        b"NoSuchOp", 0, None, None, ctypes.byref(bad)) == 0, err()
    rc = lib.MXTPUSymbolCompose(bad, b"x", 1, ckeys, args)
    assert rc != 0 and "NoSuchOp" in err()

    # an uncomposed atomic handle gives a meaningful error elsewhere
    rc = lib.MXTPUSymbolListArguments(bad, ctypes.byref(n),
                                      ctypes.byref(names))
    assert rc != 0 and "uncomposed" in err()

    # out-of-range slice errors instead of silently clamping
    hbad = ctypes.c_void_p()
    rc = lib.MXTPUNDArraySlice(hx, 0, 100, ctypes.byref(hbad))
    assert rc != 0 and "invalid slice" in err()

    # compose also wires free variables of a REAL (JSON-loaded) symbol
    json_sym = ctypes.c_void_p()
    assert lib.MXTPUSymbolSaveToJSON(fc, ctypes.byref(out)) == 0, err()
    assert lib.MXTPUSymbolCreateFromJSON(
        out.value, ctypes.byref(json_sym)) == 0, err()
    scaled = ctypes.c_void_p()
    k2 = (ctypes.c_char_p * 2)(b"data", b"scalar")
    v2 = (ctypes.c_char_p * 2)(b"", b"2.0")
    # graft: data := data * 2 via an atomic _mul_scalar, composed into
    # the loaded graph's free 'data' variable
    assert lib.MXTPUSymbolCreateAtomicSymbol(
        b"_mul_scalar", 1, (ctypes.c_char_p * 1)(b"scalar"),
        (ctypes.c_char_p * 1)(b"2.0"), ctypes.byref(scaled)) == 0, err()
    assert lib.MXTPUSymbolCompose(scaled, b"x2", 1, ckeys, args) == 0, err()
    sub_args = (ctypes.c_void_p * 1)(scaled)
    assert lib.MXTPUSymbolCompose(json_sym, b"", 1, ckeys,
                                  sub_args) == 0, err()
    ex3 = ctypes.c_void_p()
    assert lib.MXTPUExecutorBind(json_sym, 1, 0, 2, arg_handles, None,
                                 None, 0, None, ctypes.byref(ex3)) == 0, \
        err()
    assert lib.MXTPUExecutorForward(ex3, 0) == 0, err()
    n3 = ctypes.c_uint32()
    outs3 = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXTPUExecutorOutputs(
        ex3, ctypes.byref(n3), ctypes.byref(outs3)) == 0, err()
    got3 = np.zeros((4, 3), np.float32)
    assert lib.MXTPUNDArraySyncCopyToCPU(
        ctypes.c_void_p(outs3[0]),
        got3.ctypes.data_as(ctypes.c_void_p), got3.nbytes) == 0
    np.testing.assert_allclose(got3, (2 * xv) @ wv.T, rtol=1e-4,
                               atol=1e-5)


def test_cpp_frontend(tmp_path):
    """Compile and run the header-only C++ frontend demo (cpp-package
    parity — reference cpp-package/include/mxnet-cpp + example/mlp.cpp):
    Operator/Symbol graph building, Executor train loop, imperative
    sgd_update, JSON round-trip, all from a C++ program."""
    run = _compile_and_run(os.path.join("examples", "cpp", "train.cpp"),
                           [], compiler="g++", std="c++17", timeout=300)
    assert "cpp frontend ok" in run.stdout

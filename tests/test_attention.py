"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU backend exercises the real kernel logic)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.attention import flash_attention, _reference_attention
from mxnet_tpu.test_utils import assert_almost_equal


def _qkv(b=2, s=128, h=2, d=32, seed=0):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _reference_attention(q, k, v, causal, 1.0 / np.sqrt(q.shape[-1]))
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_flash_gradients_match():
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(s=64)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def f(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=32,
                               block_k=32).sum()

    def f_ref(q, k, v):
        return _reference_attention(q, k, v, True, scale).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-4)


def test_flash_op_registered():
    rng = np.random.RandomState(0)
    q = nd.array(rng.randn(1, 64, 2, 32).astype(np.float32))
    k = nd.array(rng.randn(1, 64, 2, 32).astype(np.float32))
    v = nd.array(rng.randn(1, 64, 2, 32).astype(np.float32))
    out = nd._contrib_FlashAttention(q, k, v, causal=True, block_q=32,
                                     block_k=32)
    ref = _reference_attention(q._data, k._data, v._data, True,
                               1.0 / np.sqrt(32))
    assert_almost_equal(out.asnumpy(), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_in_ulysses():
    """flash kernel as the local attention inside all-to-all sequence
    parallelism."""
    import jax.numpy as jnp

    from mxnet_tpu import parallel

    q, k, v = _qkv(s=128, h=8)
    mesh = parallel.make_mesh({"seq": 8})
    ref = _reference_attention(q, k, v, True, 1.0 / np.sqrt(q.shape[-1]))

    def attn(q, k, v, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=32, block_k=32)

    out = parallel.ulysses_attention(q, k, v, mesh, causal=True,
                                     attn_fn=attn)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)

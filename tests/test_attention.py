"""Pallas flash-attention kernel vs the dense oracle (interpret mode on the
CPU backend exercises the real kernel logic)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.attention import flash_attention, _reference_attention
from mxnet_tpu.test_utils import assert_almost_equal


def _qkv(b=2, s=128, h=2, d=32, seed=0):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = _reference_attention(q, k, v, causal, 1.0 / np.sqrt(q.shape[-1]))
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_flash_gradients_match():
    import jax
    import jax.numpy as jnp

    q, k, v = _qkv(s=64)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def f(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=32,
                               block_k=32).sum()

    def f_ref(q, k, v):
        return _reference_attention(q, k, v, True, scale).sum()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-4)


def test_flash_op_registered():
    rng = np.random.RandomState(0)
    q = nd.array(rng.randn(1, 64, 2, 32).astype(np.float32))
    k = nd.array(rng.randn(1, 64, 2, 32).astype(np.float32))
    v = nd.array(rng.randn(1, 64, 2, 32).astype(np.float32))
    out = nd._contrib_FlashAttention(q, k, v, causal=True, block_q=32,
                                     block_k=32)
    ref = _reference_attention(q._data, k._data, v._data, True,
                               1.0 / np.sqrt(32))
    assert_almost_equal(out.asnumpy(), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_flash_in_ulysses():
    """flash kernel as the local attention inside all-to-all sequence
    parallelism."""
    import jax.numpy as jnp

    from mxnet_tpu import parallel

    q, k, v = _qkv(s=128, h=8)
    mesh = parallel.make_mesh({"seq": 8})
    ref = _reference_attention(q, k, v, True, 1.0 / np.sqrt(q.shape[-1]))

    def attn(q, k, v, causal, scale):
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=32, block_k=32)

    out = parallel.ulysses_attention(q, k, v, mesh, causal=True,
                                     attn_fn=attn)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-5)


def test_flash_backward_kernel_vs_dense_oracle():
    """The Pallas backward kernels (dQ + dK/dV, flash-v2 schedule) must
    match the dense vjp across causal/non-causal, rectangular seqs, and
    bf16 — and they ARE the training path (custom_vjp uses the kernels,
    not the dense oracle)."""
    import jax
    import jax.numpy as jnp

    np.random.seed(0)
    configs = [
        (2, 16, 16, 2, 8, True, jnp.float32, 2e-4),
        (1, 32, 16, 1, 8, False, jnp.float32, 2e-4),
        (2, 24, 24, 2, 4, True, jnp.float32, 2e-4),
        (1, 16, 16, 2, 8, True, jnp.bfloat16, 2e-2),
    ]
    for b, s, sk, h, d, causal, dt, tol in configs:
        q = jnp.asarray(np.random.randn(b, s, h, d).astype("f") * 0.4, dt)
        k = jnp.asarray(np.random.randn(b, sk, h, d).astype("f") * 0.4, dt)
        v = jnp.asarray(np.random.randn(b, sk, h, d).astype("f") * 0.4, dt)

        def f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, block_q=8,
                block_k=8).astype(jnp.float32) ** 2)

        def g(q, k, v):
            return jnp.sum(_reference_attention(
                q, k, v, causal, 1.0 / np.sqrt(d)).astype(jnp.float32) ** 2)

        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gg = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
        for name, a, bb in zip("qkv", gf, gg):
            err = float(jnp.max(jnp.abs(
                a.astype(jnp.float32) - bb.astype(jnp.float32))))
            ref = float(jnp.max(jnp.abs(bb.astype(jnp.float32)))) + 1e-6
            assert err / ref < tol, (name, causal, dt, err / ref)


def test_flash_long_sequence_train_step():
    """Long-sequence training step through the kernel path: K/V stream
    block-by-block (nothing whole-sequence is staged in VMEM), so seq
    length is HBM-bound.  16k+ on the TPU chip; a shorter structural run
    on the CPU interpreter."""
    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() == "tpu"
    s = 16384 if on_tpu else 256
    b, h, d = 1, 2, 64
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, s, h, d), dt) * 0.2
    k = jax.random.normal(key, (b, s, h, d), dt) * 0.2
    v = jax.random.normal(key, (b, s, h, d), dt) * 0.2
    w = jnp.eye(d, dtype=dt)

    def loss(w, q, k, v):
        o = flash_attention(q @ w, k, v, causal=True)
        return jnp.mean(o.astype(jnp.float32) ** 2)

    step = jax.jit(jax.value_and_grad(loss))
    val, grad = step(w, q, k, v)
    gnorm = float(jnp.linalg.norm(grad.astype(jnp.float32)))
    assert np.isfinite(float(val)) and gnorm > 0
    # normalized step so the loss moves resolvably in f32
    val2, _ = step(w - (0.05 / gnorm) * grad.astype(dt), q, k, v)
    assert np.isfinite(float(val2))
    assert float(val2) < float(val)

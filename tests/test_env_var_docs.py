"""Docs lint: every MXNET_*/MXTPU_* environment variable the framework
actually reads (or registers) must have a row — or at least a mention —
in docs/how_to/env_var.md.  Catches the recurring drift where a new knob
ships without documentation."""
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "how_to", "env_var.md")

_VAR = re.compile(r"\b((?:MXNET|MXTPU)_[A-Z0-9]+(?:_[A-Z0-9]+)*)\b")
# a line must actually READ or DECLARE the variable: plain docstring
# mentions (e.g. reference C-macro names like MXNET_REGISTER_OP_PROPERTY)
# are not env vars
_USE = re.compile(r"register_env\(|environ|(?<![_A-Za-z])env\(")


def _referenced_vars():
    found = {}
    for dirpath, _dirnames, filenames in os.walk(
            os.path.join(REPO, "mxnet_tpu")):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    if not _USE.search(line):
                        continue
                    for m in _VAR.finditer(line):
                        found.setdefault(
                            m.group(1),
                            "%s:%d" % (os.path.relpath(path, REPO), lineno))
    return found


def test_every_env_var_is_documented():
    with open(DOC) as f:
        doc = f.read()
    documented = set(_VAR.findall(doc))
    referenced = _referenced_vars()
    missing = {v: at for v, at in sorted(referenced.items())
               if v not in documented}
    assert not missing, (
        "env vars read in mxnet_tpu/ but absent from "
        "docs/how_to/env_var.md:\n" + "\n".join(
            "  %s (first use: %s)" % (v, at)
            for v, at in sorted(missing.items())))


def test_lint_catches_known_vars():
    # the scanner itself must see through both idioms or the lint is moot
    referenced = _referenced_vars()
    assert "MXNET_TELEMETRY" in referenced           # register_env(...)
    assert "MXNET_KVSTORE_SYNC" in referenced        # os.environ.get(...)

"""Fused train step (Executor.fused_step): the single-program
fwd+bwd+optimizer path must match the reference-style eager per-key loop
(MXNET_FUSED_STEP=0) bit-for-bit in f32, across optimizers, and support the
bf16 compute_dtype mixed-precision mode."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _make_module(seed=0, compute_dtype=None, optimizer="sgd", opt_params=None,
                 fused=True):
    os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
    try:
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
        net = mx.sym.Activation(net, name="relu1", act_type="relu")
        net = mx.sym.BatchNorm(net, name="bn1")
        net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu(),
                            compute_dtype=compute_dtype)
        mod.bind(data_shapes=[("data", (8, 10))],
                 label_shapes=[("softmax_label", (8,))])
        mx.random.seed(seed)
        np.random.seed(seed)
        mod.init_params(initializer=mx.init.Xavier(), force_init=True)
        params = opt_params or {"learning_rate": 0.05}
        mod.init_optimizer(optimizer=optimizer, optimizer_params=params,
                           force_init=True)
    finally:
        os.environ.pop("MXNET_FUSED_STEP", None)
    return mod


def _run_steps(mod, n=3, seed=0):
    rng = np.random.RandomState(seed)
    metric = mx.metric.Accuracy()
    for _ in range(n):
        x = mx.nd.array(rng.randn(8, 10).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))
        batch = mx.io.DataBatch(data=[x], label=[y], pad=0)
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)
    args, auxs = mod.get_params()
    return ({k: v.asnumpy() for k, v in args.items()},
            {k: v.asnumpy() for k, v in auxs.items()},
            metric.get()[1])


OPTIMIZERS = [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-3}),
    ("sgd", {"learning_rate": 0.05}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
    ("adadelta", {}),
    ("dcasgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("test", {}),
]


@pytest.mark.parametrize("opt,params", OPTIMIZERS)
def test_fused_matches_eager(opt, params):
    mod_f = _make_module(optimizer=opt, opt_params=dict(params), fused=True)
    assert mod_f._fused_ok, "fused path should be active for %s" % opt
    args_f, aux_f, acc_f = _run_steps(mod_f)

    mod_e = _make_module(optimizer=opt, opt_params=dict(params), fused=False)
    assert not mod_e._fused_ok
    args_e, aux_e, acc_e = _run_steps(mod_e)

    for k in args_e:
        np.testing.assert_allclose(args_f[k], args_e[k], rtol=2e-5,
                                   atol=2e-6, err_msg=k)
    for k in aux_e:
        np.testing.assert_allclose(aux_f[k], aux_e[k], rtol=2e-5,
                                   atol=2e-6, err_msg=k)
    assert acc_f == pytest.approx(acc_e)


def test_fused_sgld_runs():
    mod = _make_module(optimizer="sgld",
                       opt_params={"learning_rate": 1e-3}, fused=True)
    assert mod._fused_ok
    args, _, _ = _run_steps(mod)
    for v in args.values():
        assert np.all(np.isfinite(v))


def test_fused_bf16_compute():
    mod = _make_module(compute_dtype="bfloat16", fused=True)
    assert mod._fused_ok
    args, auxs, _ = _run_steps(mod, n=5)
    # master params stay f32 and finite; BN moving stats stay f32
    for v in args.values():
        assert v.dtype == np.float32
        assert np.all(np.isfinite(v))
    for v in auxs.values():
        assert v.dtype == np.float32


def test_fused_converges():
    # tiny 2-class problem learnable in a few steps through the fused path
    rng = np.random.RandomState(3)
    x = rng.randn(64, 10).astype(np.float32)
    w = rng.randn(10)
    y = (x @ w > 0).astype(np.float32)
    mod = _make_module(optimizer="sgd",
                       opt_params={"learning_rate": 0.1, "momentum": 0.9})
    metric = mx.metric.Accuracy()
    for i in range(200):
        b = mx.io.DataBatch(data=[mx.nd.array(x[(i % 8) * 8:(i % 8 + 1) * 8])],
                            label=[mx.nd.array(y[(i % 8) * 8:(i % 8 + 1) * 8])],
                            pad=0)
        mod.forward_backward(b)
        mod.update()
    metric.reset()
    for i in range(8):
        b = mx.io.DataBatch(data=[mx.nd.array(x[i * 8:(i + 1) * 8])],
                            label=[mx.nd.array(y[i * 8:(i + 1) * 8])], pad=0)
        mod.forward(b, is_train=False)
        mod.update_metric(metric, b.label)
    assert metric.get()[1] > 0.9


def test_fused_lr_scheduler_no_retrace():
    # scheduler-driven lr changes must not recompile: lr is a traced scalar
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    mod = _make_module(optimizer="sgd",
                       opt_params={"learning_rate": 0.1,
                                   "lr_scheduler": sched})
    _run_steps(mod, n=6)
    execu = mod._exec_group.execs[0]
    fused_keys = [k for k in execu._jit_cache if k[0] == "fused"]
    assert len(fused_keys) == 1
    assert mod._optimizer.num_update == 6


def test_fused_outputs_before_update_fall_back():
    # reading outputs between forward_backward and update falls back to the
    # two-phase path for that batch, keeping semantics
    mod = _make_module()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 10).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))
    batch = mx.io.DataBatch(data=[x], label=[y], pad=0)
    mod.forward_backward(batch)
    outs = mod.get_outputs()
    assert outs[0].shape == (8, 4)
    mod.update()  # eager path applies the materialized grads
    args, _ = mod.get_params()
    assert all(np.all(np.isfinite(v.asnumpy())) for v in args.values())


def test_fused_optimizer_state_save_load(tmp_path):
    mod = _make_module(optimizer="adam", opt_params={"learning_rate": 0.01})
    _run_steps(mod, n=2)
    f = str(tmp_path / "opt.states")
    mod.save_optimizer_states(f)
    mod2 = _make_module(optimizer="adam", opt_params={"learning_rate": 0.01})
    _run_steps(mod2, n=1)
    mod2.load_optimizer_states(f)
    s1 = mod._updater.states
    s2 = mod2._updater.states
    assert set(s1) == set(s2)
    for k in s1:
        m1, v1 = s1[k]
        m2, v2 = s2[k]
        np.testing.assert_allclose(m1.asnumpy(), m2.asnumpy())
        np.testing.assert_allclose(v1.asnumpy(), v2.asnumpy())

"""Multi-process dist_sync tests: tools/launch.py spawns 4 local worker
processes that rendezvous via jax.distributed and assert sync-sum semantics
(reference: tests/nightly/test_all.sh:37 running
``launch.py -n 4 python dist_sync_kvstore.py``)."""
import os
import re
import subprocess
import sys

import pytest

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _ok_ranks(stdout, worker_name):
    """Distinct worker ranks that reported OK. Robust to concurrent workers
    interleaving their stdout writes onto one line (print() issues the text
    and the newline as separate write()s), which breaks per-line counting."""
    return {int(m.group(1)) for m in
            re.finditer(r"%s (\d+)/\d+ OK" % re.escape(worker_name), stdout)}


def _run_launcher(nworkers, script, timeout=240):
    env = dict(os.environ)
    env.pop("DMLC_ROLE", None)
    # subprocesses must not inherit the 8-virtual-device flag: each worker
    # is one process with one CPU device
    env["XLA_FLAGS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(nworkers), sys.executable, script],
        env=env, cwd=ROOT, capture_output=True, text=True, timeout=timeout)


def test_dist_sync_kvstore_4_workers():
    res = _run_launcher(4, os.path.join(ROOT, "tests", "dist_sync_worker.py"))
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    assert _ok_ranks(res.stdout, "dist_sync_worker") == {0, 1, 2, 3}, \
        res.stdout


def test_dist_sync_in_process_single_worker():
    # single-process fallback: dist_sync degrades to local semantics
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kvstore.create("dist_sync")
    assert kv.type == "dist_sync"
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init(0, mx.nd.ones((2, 2)))
    kv.push(0, mx.nd.ones((2, 2)) * 3)
    out = mx.nd.zeros((2, 2))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3, np.float32))
    assert kv.get_num_dead_node() == 0


def test_dist_sync_module_training_4_workers():
    res = _run_launcher(4, os.path.join(ROOT, "tests", "dist_train_worker.py"))
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    assert _ok_ranks(res.stdout, "dist_train_worker") == {0, 1, 2, 3}, \
        res.stdout


def test_dist_fused_global_mesh_4_workers():
    """The fused path: fwd+bwd+psum+update as ONE program over a mesh
    spanning 4 processes, params matching a single-process oracle."""
    res = _run_launcher(4, os.path.join(ROOT, "tests", "dist_fused_worker.py"))
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    assert _ok_ranks(res.stdout, "dist_fused_worker") == {0, 1, 2, 3}, \
        res.stdout

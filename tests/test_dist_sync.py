"""Multi-process dist_sync tests: tools/launch.py spawns 4 local worker
processes that rendezvous via jax.distributed and assert sync-sum semantics
(reference: tests/nightly/test_all.sh:37 running
``launch.py -n 4 python dist_sync_kvstore.py``)."""
import os
import re
import subprocess
import sys

import pytest

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _ok_ranks(stdout, worker_name):
    """Distinct worker ranks that reported OK. Robust to concurrent workers
    interleaving their stdout writes onto one line (print() issues the text
    and the newline as separate write()s), which breaks per-line counting."""
    return {int(m.group(1)) for m in
            re.finditer(r"%s (\d+)/\d+ OK" % re.escape(worker_name), stdout)}


def _run_launcher(nworkers, script, timeout=240):
    env = dict(os.environ)
    env.pop("DMLC_ROLE", None)
    # subprocesses must not inherit the 8-virtual-device flag: each worker
    # is one process with one CPU device
    env["XLA_FLAGS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(nworkers), sys.executable, script],
        env=env, cwd=ROOT, capture_output=True, text=True, timeout=timeout)


def test_dist_sync_kvstore_4_workers():
    res = _run_launcher(4, os.path.join(ROOT, "tests", "dist_sync_worker.py"))
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    assert _ok_ranks(res.stdout, "dist_sync_worker") == {0, 1, 2, 3}, \
        res.stdout


def test_dist_sync_in_process_single_worker():
    # single-process fallback: dist_sync degrades to local semantics
    import mxnet_tpu as mx
    import numpy as np

    kv = mx.kvstore.create("dist_sync")
    assert kv.type == "dist_sync"
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init(0, mx.nd.ones((2, 2)))
    kv.push(0, mx.nd.ones((2, 2)) * 3)
    out = mx.nd.zeros((2, 2))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 3, np.float32))
    assert kv.get_num_dead_node() == 0


def test_dist_sync_module_training_4_workers():
    res = _run_launcher(4, os.path.join(ROOT, "tests", "dist_train_worker.py"))
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    assert _ok_ranks(res.stdout, "dist_train_worker") == {0, 1, 2, 3}, \
        res.stdout


def test_dist_fused_global_mesh_4_workers():
    """The fused path: fwd+bwd+psum+update as ONE program over a mesh
    spanning 4 processes, params matching a single-process oracle."""
    res = _run_launcher(4, os.path.join(ROOT, "tests", "dist_fused_worker.py"))
    assert res.returncode == 0, (res.stdout[-3000:], res.stderr[-3000:])
    assert _ok_ranks(res.stdout, "dist_fused_worker") == {0, 1, 2, 3}, \
        res.stdout


def test_ssh_launcher_mode(tmp_path):
    """--launcher ssh: one process per hostfile entry via ssh, env inlined
    into the remote command (reference tools/launch.py ssh mode). sshd is
    unavailable in CI, so a stub `ssh` on PATH captures the wire command
    and executes the remote part locally — validating host assignment,
    the DMLC env contract, and quoting end to end."""
    stub_dir = tmp_path / "bin"
    stub_dir.mkdir()
    log = tmp_path / "ssh_calls.log"
    stub = stub_dir / "ssh"
    # stub contract: ssh -o X -p PORT HOST REMOTE_CMD -> run REMOTE_CMD
    stub.write_text(
        "#!/bin/bash\n"
        "shift 2  # -o StrictHostKeyChecking=no\n"
        "shift 2  # -p PORT\n"
        "host=$1; shift\n"
        "echo \"$host\" >> %s\n"
        "exec bash -c \"$1\"\n" % log)
    stub.chmod(0o755)

    hostfile = tmp_path / "hosts"
    hostfile.write_text("hostA\nhostB\n# a comment\n")
    outdir = tmp_path / "out"
    outdir.mkdir()
    worker = tmp_path / "worker.sh"
    worker.write_text(
        "#!/bin/bash\n"
        "echo \"$DMLC_ROLE $DMLC_WORKER_ID $DMLC_NUM_WORKER "
        "$DMLC_PS_ROOT_PORT\" > %s/w$DMLC_WORKER_ID\n" % outdir)
    worker.chmod(0o755)

    env = dict(os.environ)
    env["PATH"] = "%s:%s" % (stub_dir, env["PATH"])
    env.pop("DMLC_ROLE", None)
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "--launcher", "ssh", "--hostfile", str(hostfile),
         "-n", "4", "bash", str(worker)],
        env=env, cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, (res.stdout, res.stderr)

    # workers round-robin over the two hosts
    calls = log.read_text().split()
    assert sorted(calls) == ["hostA", "hostA", "hostB", "hostB"]
    # every worker got a distinct id and the same rendezvous contract
    seen = {}
    for i in range(4):
        role, wid, nw, port = (outdir / ("w%d" % i)).read_text().split()
        assert role == "worker" and int(wid) == i and nw == "4"
        seen.setdefault("port", port)
        assert port == seen["port"]


def test_auto_resume_kill_relaunch_converge(tmp_path):
    """Checkpoint-based fault tolerance end to end: the worker dies hard
    (os._exit 17) after epoch 2; launch.py --auto-resume relaunches it;
    the relaunch resumes from the newest checkpoint via
    mx.model.find_latest_checkpoint and converges (reference mechanism:
    fit.py --load-epoch, example/image-classification/common/fit.py)."""
    import json

    env = dict(os.environ)
    env.pop("DMLC_ROLE", None)
    env["XLA_FLAGS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "1", "--auto-resume", "2",
         sys.executable, os.path.join(ROOT, "tests", "autoresume_worker.py"),
         str(tmp_path)],
        env=env, cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert "relaunch 1/2" in res.stderr

    with open(tmp_path / "result.json") as f:
        result = json.load(f)
    # the surviving attempt resumed from the crash-epoch checkpoint
    assert result["attempt"] == 1
    assert result["resumed_from"] == 2
    assert result["acc"] > 0.9, result
    # checkpoints for both attempts' epochs exist (2 from attempt 0)
    import mxnet_tpu as mx
    assert mx.model.find_latest_checkpoint(str(tmp_path / "ar")) == 10


def test_ssh_launcher_publishes_server_uris(tmp_path):
    """ssh mode with parameter servers: the launcher must publish the
    authoritative DMLC_SERVER_URIS list (hosts round-robin, root_port+i)
    to every process — workers cannot derive server placement from the
    root URI alone (kvstore.py DistAsyncKVStore address derivation)."""
    stub_dir = tmp_path / "bin"
    stub_dir.mkdir()
    stub = stub_dir / "ssh"
    stub.write_text("#!/bin/bash\nshift 4\nhost=$1; shift\n"
                    "exec bash -c \"$1\"\n")
    stub.chmod(0o755)
    hostfile = tmp_path / "hosts"
    hostfile.write_text("hostA\nhostB\n")
    outdir = tmp_path / "out"
    outdir.mkdir()
    prog = tmp_path / "prog.sh"
    prog.write_text(
        "#!/bin/bash\n"
        "echo \"$DMLC_ROLE $DMLC_SERVER_ID$DMLC_WORKER_ID "
        "$DMLC_SERVER_URIS $DMLC_PS_ROOT_URI\" "
        ">> %s/$DMLC_ROLE-$DMLC_SERVER_ID$DMLC_WORKER_ID\n" % outdir)
    prog.chmod(0o755)

    env = dict(os.environ)
    env["PATH"] = "%s:%s" % (stub_dir, env["PATH"])
    env.pop("DMLC_ROLE", None)
    env.pop("DMLC_PS_ROOT_URI", None)  # launch.py prefers an inherited URI
    env["DMLC_PS_ROOT_PORT"] = "9500"
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "--launcher", "ssh", "--hostfile", str(hostfile),
         "-n", "2", "-s", "2", "bash", str(prog)],
        env=env, cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, (res.stdout, res.stderr)

    uris = "hostA:9500,hostB:9501"
    for fname in ("server-0", "server-1", "worker-0", "worker-1"):
        role, rid, got_uris, root = \
            (outdir / fname).read_text().split()
        assert got_uris == uris, (fname, got_uris)
        assert root == "hostA"  # coordinator on the first hostfile entry

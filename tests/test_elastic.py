"""Elastic worker membership tests — the kvstore shrinks and grows
instead of dying (docs/how_to/fault_tolerance.md §elasticity).

Unit tests drive the in-process sync-mode KVStoreServer through the
join/leave/evict RPCs and assert the membership-sized merge rounds,
renormalization, barrier re-forming, and snapshot persistence.  The
end-to-end churn test replays the ``membership-churn`` chaos scenario
(tools/chaos_run.py): kill -9 one of three workers under a seeded
FaultPlan, evict it, finish on two with renormalized gradients, then
grow back to three with a mid-run joiner.
"""
import os
import signal
import socket
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore_server as kvs

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _join_all(host, port, ranks):
    clients = {}
    for r in ranks:
        c = kvs.ServerClient(host, port)
        c.join(r)
        clients[r] = c
    return clients


def _close_all(clients):
    for c in clients.values():
        c.close()


def test_join_leave_generations():
    srv = kvs.start_server(num_workers=3, sync_mode=True)
    host, port = srv.addr
    try:
        clients = _join_all(host, port, [0, 1, 2])
        view = clients[0].membership()
        assert view["ranks"] == [0, 1, 2]
        assert view["gen"] == 3  # one bump per fresh join
        assert view["num_workers"] == 3
        # re-join of a live rank is idempotent: no generation churn
        clients[1].join(1)
        assert clients[0].membership()["gen"] == 3
        clients[2].leave(2)
        view = clients[0].membership()
        assert view["ranks"] == [0, 1]
        assert view["gen"] == 4
        # leave of a gone rank is idempotent too
        clients[2].leave(2)
        assert clients[0].membership()["gen"] == 4
        _close_all(clients)
    finally:
        srv.stop()


def test_shrink_renormalizes_merge_rounds():
    """A 2-of-3 round must apply num_workers/len(round) times the merged
    gradient — otherwise a shrink silently scales the effective learning
    rate down (workers average by the launch-time fleet size)."""
    srv = kvs.start_server(num_workers=3, sync_mode=True)
    host, port = srv.addr
    try:
        clients = _join_all(host, port, [0, 1, 2])
        clients[0].init(0, np.zeros(4, np.float32))
        for r in (0, 1, 2):
            clients[r].push(0, np.ones(4, np.float32), rank=r)
        np.testing.assert_allclose(clients[0].pull(0), np.full(4, 3.0))
        clients[2].leave(2)
        for r in (0, 1):
            clients[r].push(0, np.ones(4, np.float32), rank=r)
        # 2 contributions renormalized by 3/2 -> the same +3.0 per round
        np.testing.assert_allclose(clients[0].pull(0), np.full(4, 6.0))
        assert srv.round_sizes == {3: 1, 2: 1}
        # a push from the departed rank is discarded, not merged
        clients[2].push(0, np.full(4, 100.0, np.float32), rank=2)
        for r in (0, 1):
            clients[r].push(0, np.ones(4, np.float32), rank=r)
        np.testing.assert_allclose(clients[0].pull(0), np.full(4, 9.0))
        _close_all(clients)
    finally:
        srv.stop()


def test_midrun_join_counts_full_round():
    """Acceptance: after a new worker joins, the next sync-merge round
    waits for and counts ALL live contributions — no barrier timeout, no
    job restart."""
    srv = kvs.start_server(num_workers=3, sync_mode=True)
    host, port = srv.addr
    try:
        clients = _join_all(host, port, [0, 1])
        clients[0].init(0, np.zeros(4, np.float32))
        joiner = kvs.ServerClient(host, port)
        view = joiner.join(5)
        assert view["ranks"] == [0, 1, 5]
        for r in (0, 1):
            clients[r].push(0, np.ones(4, np.float32), rank=r)
        # round must NOT flush on 2 of 3 live members
        np.testing.assert_allclose(clients[0].pull(0), np.zeros(4))
        joiner.push(0, np.ones(4, np.float32), rank=5)
        np.testing.assert_allclose(clients[0].pull(0), np.full(4, 3.0))
        assert srv.round_sizes == {3: 1}
        joiner.close()
        _close_all(clients)
    finally:
        srv.stop()


def test_barrier_reforms_around_evicted_member():
    """With eviction enabled, a heartbeat-silent member is removed and
    the parked barrier RELEASES for the survivors (the legacy path
    aborts with an error instead)."""
    srv = kvs.start_server(num_workers=2, sync_mode=True,
                           evict_timeout=0.5)
    host, port = srv.addr
    try:
        survivor = kvs.ServerClient(host, port)
        survivor.join(0)
        survivor.start_heartbeat(0, interval=0.1)
        silent = kvs.ServerClient(host, port)
        silent.join(1)
        silent.close()  # preempted without a leave RPC: heartbeats stop
        t0 = time.monotonic()
        survivor.barrier(rank=0)  # must release, not raise
        assert time.monotonic() - t0 < 10
        assert survivor.membership()["ranks"] == [0]
        survivor.close()
    finally:
        srv.stop()


def test_snapshot_roundtrips_membership(tmp_path):
    """Snapshot v3 journals the membership table; a restarted server
    re-baselines restored heartbeats so survivors are not instantly
    evicted as stale."""
    snap = str(tmp_path / "srv.snap")
    srv = kvs.start_server(num_workers=3, sync_mode=True,
                           snapshot_path=snap)
    host, port = srv.addr
    clients = _join_all(host, port, [0, 1])
    clients[0].snapshot()
    _close_all(clients)
    srv.stop()

    srv2 = kvs.start_server(num_workers=3, sync_mode=True,
                            snapshot_path=snap, evict_timeout=30.0)
    try:
        assert srv2.restored
        assert srv2._members == {0, 1}
        assert srv2._mgen == 2
        # heartbeats re-baselined to restore time, not restored stale
        assert srv2._stale_members(5.0) == []
    finally:
        srv2.stop()


def test_retry_deadline_raises_typed_error(monkeypatch):
    """MXNET_KVSTORE_RETRY_DEADLINE caps the reconnect loop by wall
    clock even when the attempt budget is far from exhausted, and the
    give-up is a typed KVStoreConnectionError (a ConnectionError, so
    existing handlers still catch it)."""
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "100000")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "20")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_DEADLINE", "0.4")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here
    t0 = time.monotonic()
    with pytest.raises(kvs.KVStoreConnectionError, match="unreachable"):
        kvs.ServerClient("127.0.0.1", port)
    assert time.monotonic() - t0 < 5
    assert issubclass(kvs.KVStoreConnectionError, ConnectionError)


def test_preemption_handler_drains_checkpoints_leaves(monkeypatch):
    """SIGTERM path: drain in-flight comm ops, run the checkpoint hook,
    then leave the membership so survivors re-form immediately."""
    monkeypatch.delenv("DMLC_PS_ROOT_URI", raising=False)
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("MXNET_KVSTORE_ELASTIC", "1")
    kv = mx.kvstore.create("dist_async")
    try:
        assert kv.membership()["ranks"] == [0]
        calls = []
        handler = mx.kvstore.install_preemption_handler(
            kv, checkpoint_fn=lambda: calls.append("ckpt"),
            exit_process=False)
        handler(signal.SIGTERM, None)
        assert calls == ["ckpt"]
        assert kv.membership()["ranks"] == []
        handler(signal.SIGTERM, None)  # idempotent on repeated signals
        assert calls == ["ckpt"]
    finally:
        kv.close()


@pytest.mark.chaos
def test_membership_churn_end_to_end_reproducible():
    """Acceptance: 3 workers mid-epoch, kill -9 one -> the job completes
    on 2 with renormalized gradients; a fresh rank joins mid-run and the
    post-join rounds count the full live set; the final weight is the
    churn-invariant value on BOTH replays of the same seed."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from chaos_run import run_membership_churn

    assert run_membership_churn(seed=2, timeout=120.0)
    assert run_membership_churn(seed=2, timeout=120.0)

"""KVStore local semantics vs numpy (reference:
tests/python/unittest/test_kvstore.py:21-40 and tests/nightly/test_kvstore.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [3, 5, 7]


def _init_kv(kv_type="local"):
    kv = mx.kv.create(kv_type)
    kv.init(KEYS, [nd.zeros(SHAPE)] * len(KEYS))
    return kv


def test_single_kv_pair():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones(SHAPE))


def test_push_aggregates():
    kv = mx.kv.create("local")
    kv.init(3, nd.zeros(SHAPE))
    # push a list of 4 devices' grads for one key -> summed
    kv.push(3, [nd.ones(SHAPE)] * 4)
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones(SHAPE) * 4)


def test_push_replaces_store_without_updater():
    # reference KVStoreLocal::Push: without an updater the store holds the
    # merged value of the LAST push, not a running accumulation
    kv = mx.kv.create("local")
    kv.init(3, nd.ones(SHAPE) * 2)
    kv.push(3, nd.ones(SHAPE) * 8)
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.full(SHAPE, 8.0))
    kv.push(3, nd.ones(SHAPE) * 5)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.full(SHAPE, 5.0))


def test_list_kv_pairs():
    kv = _init_kv()
    kv.push(KEYS, [[nd.ones(SHAPE) * 2.0]] * len(KEYS))
    outs = [nd.zeros(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        assert_almost_equal(o, np.full(SHAPE, 2.0))


def test_updater_runs_on_push():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones(SHAPE) * 4)

    def updater(key, recv, stored):
        stored += recv * 2.0

    kv._set_updater(updater)
    kv.push(3, [nd.ones(SHAPE)] * 3)  # merged = 3, stored += 6
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.full(SHAPE, 10.0))


def test_set_optimizer_applies_update():
    kv = mx.kv.create("local")
    kv.init(0, nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0,
                                      wd=0.0))
    kv.push(0, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    kv.pull(0, out=out)
    assert_almost_equal(out, np.full(SHAPE, 0.9), rtol=1e-5, atol=1e-6)


def test_device_type_same_semantics():
    kv = mx.kv.create("device")
    kv.init(3, nd.zeros(SHAPE))
    kv.push(3, [nd.ones(SHAPE)] * 2)
    out = nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.full(SHAPE, 2.0))
    assert kv.type == "device"


def test_rank_and_num_workers_local():
    kv = mx.kv.create("local")
    assert kv.rank == 0
    assert kv.num_workers == 1


def test_string_keys():
    kv = mx.kv.create("local")
    kv.init("weight", nd.ones((2, 2)))
    out = nd.zeros((2, 2))
    kv.pull("weight", out=out)
    assert_almost_equal(out, np.ones((2, 2)))


def test_duplicate_init_raises():
    kv = mx.kv.create("local")
    kv.init(1, nd.zeros((2,)))
    with pytest.raises(mx.MXNetError):
        kv.init(1, nd.zeros((2,)))


def test_push_before_init_raises():
    kv = mx.kv.create("local")
    with pytest.raises(mx.MXNetError):
        kv.push(9, nd.ones((2,)))

"""Orbax-backed sharded checkpointing (SURVEY §5.4's TPU-native complement
to the .params format): train -> sharded save -> restore (optionally onto a
mesh) -> outputs match."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _small_module(tmp_path):
    np.random.seed(0)
    X = np.random.randn(40, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=10)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    return mod, X


def test_sharded_checkpoint_roundtrip(tmp_path):
    mod, X = _small_module(tmp_path)
    args, auxs = mod.get_params()
    prefix = str(tmp_path / "model")
    path = mx.checkpoint.save_sharded_checkpoint(prefix, 2, mod.symbol,
                                                 args, auxs)
    assert path.endswith("-0002.orbax")

    sym2, args2, auxs2 = mx.checkpoint.load_sharded_checkpoint(prefix, 2)
    assert sym2 is not None
    for k in args:
        np.testing.assert_allclose(args2[k].asnumpy(), args[k].asnumpy(),
                                   rtol=1e-6)
    for k in auxs:
        np.testing.assert_allclose(auxs2[k].asnumpy(), auxs[k].asnumpy(),
                                   rtol=1e-6)
    # restored params serve identical predictions via Predictor
    params = {("arg:%s" % k): v for k, v in args2.items()}
    params.update({("aux:%s" % k): v for k, v in auxs2.items()})
    pred = mx.Predictor(sym2, params, {"data": (10, 6),
                                       "softmax_label": (10,)})
    want_pred = mx.Predictor(mod.symbol,
                             {**{("arg:%s" % k): v for k, v in args.items()},
                              **{("aux:%s" % k): v for k, v in auxs.items()}},
                             {"data": (10, 6), "softmax_label": (10,)})
    np.testing.assert_allclose(pred.forward(data=X[:10])[0].asnumpy(),
                               want_pred.forward(data=X[:10])[0].asnumpy(),
                               rtol=1e-6)


def test_sharded_checkpoint_restore_onto_mesh(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mod, _ = _small_module(tmp_path)
    args, auxs = mod.get_params()
    prefix = str(tmp_path / "meshmodel")
    mx.checkpoint.save_sharded_checkpoint(prefix, 1, None, args, auxs)

    mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
    shardings = {"arg": {"fc1_weight": NamedSharding(mesh, P("model", None))}}
    _, args2, _ = mx.checkpoint.load_sharded_checkpoint(prefix, 1,
                                                        shardings=shardings)
    w = args2["fc1_weight"]._data
    assert w.sharding == shardings["arg"]["fc1_weight"]
    np.testing.assert_allclose(np.asarray(w), args["fc1_weight"].asnumpy(),
                               rtol=1e-6)


def test_sharded_checkpoint_missing(tmp_path):
    with pytest.raises(mx.base.MXNetError, match="no sharded checkpoint"):
        mx.checkpoint.load_sharded_checkpoint(str(tmp_path / "nope"), 0)


def _megatron_lm_module():
    from mxnet_tpu import sharding
    from mxnet_tpu.models.transformer import get_transformer_lm

    net = get_transformer_lm(vocab_size=64, num_layers=1, num_heads=2,
                             hidden=32, seq_len=16, block_q=16, block_k=16)
    mesh = sharding.build_mesh("data=-1,model=2")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8, 16))],
             mesh=mesh, partition_rules="transformer_megatron")
    mod.init_params(initializer=mx.init.Xavier(magnitude=2.0))
    return mod


def test_partition_spec_metadata_roundtrip_onto_fresh_mesh(tmp_path):
    """Tensor-parallel save -> spec metadata on disk -> restore onto a
    FRESH mesh reproduces the layout without explicit shardings."""
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu import sharding

    mod = _megatron_lm_module()
    executor = mod._exec_group.execs[0]
    args = {k: executor.arg_dict[k] for k in mod._exec_group.param_names}
    auxs = {k: executor.aux_dict[k] for k in mod._exec_group.aux_names}
    prefix = str(tmp_path / "tp")
    mx.checkpoint.save_sharded_checkpoint(prefix, 3, mod.symbol, args, auxs)

    specs = mx.checkpoint.load_partition_specs(prefix, 3)
    assert specs["arg"]["layer0_qkv_weight"] == P("model", None)
    assert specs["arg"]["layer0_proj_weight"] == P(None, "model")
    assert specs["arg"]["ln_f_gamma"] == P()

    fresh = sharding.build_mesh("data=-1,model=2")
    _, args2, _ = mx.checkpoint.load_sharded_checkpoint(prefix, 3, mesh=fresh)
    w = args2["layer0_qkv_weight"]._data
    assert w.sharding.mesh is fresh.abstract_mesh or \
        sharding.mesh_axes(w.sharding.mesh) == {"data": 4, "model": 2}
    assert w.sharding.spec == P("model", None)
    assert {tuple(s.data.shape) for s in w.addressable_shards} == {(48, 32)}
    np.testing.assert_allclose(
        np.asarray(w), args["layer0_qkv_weight"].asnumpy(), rtol=1e-6)


def test_mesh_restore_rejects_unknown_axis(tmp_path):
    from jax.sharding import Mesh

    import jax

    mod = _megatron_lm_module()
    executor = mod._exec_group.execs[0]
    args = {k: executor.arg_dict[k] for k in mod._exec_group.param_names}
    prefix = str(tmp_path / "tp2")
    mx.checkpoint.save_sharded_checkpoint(prefix, 1, None, args, {})

    wrong = Mesh(np.array(jax.devices()).reshape(4, 2), ("x", "y"))
    with pytest.raises(mx.base.MXNetError, match="mesh axes"):
        mx.checkpoint.load_sharded_checkpoint(prefix, 1, mesh=wrong)

"""Sequence-parallel long-context LM training end to end
(examples/transformer/train_lm_longctx.py): activations sequence-sharded
over a ('data','seq') mesh, ring_flash_attention fwd+bwd, loss falls."""
import os
import sys

import pytest

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)


def test_longctx_seq_parallel_training_loss_falls():
    from examples.transformer import train_lm_longctx

    losses = train_lm_longctx.main([
        "--seq-len", "128", "--seq-shards", "4", "--block", "32",
        "--steps", "5", "--hidden", "64", "--heads", "2", "--layers", "1",
        "--vocab-size", "32", "--batch", "1", "--lr", "0.05"])
    assert losses[-1] < losses[0] * 0.85, losses

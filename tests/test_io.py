"""Data iterator + RecordIO tests
(reference: tests/python/unittest/test_io.py + test_recordio.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarray_iter_basic():
    X = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    assert_almost_equal(batches[0].data[0], X[:5])
    assert_almost_equal(batches[0].label[0], y[:5])


def test_ndarray_iter_pad():
    X = np.arange(28, dtype=np.float32).reshape(7, 4)
    it = mx.io.NDArrayIter(X, np.zeros(7, np.float32), batch_size=5,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 2
    assert batches[1].pad == 3


def test_ndarray_iter_discard():
    X = np.arange(28, dtype=np.float32).reshape(7, 4)
    it = mx.io.NDArrayIter(X, np.zeros(7, np.float32), batch_size=5,
                           last_batch_handle="discard")
    assert len(list(it)) == 1


def test_ndarray_iter_shuffle_covers_all():
    X = np.arange(20, dtype=np.float32).reshape(20, 1)
    it = mx.io.NDArrayIter(X, np.arange(20, dtype=np.float32), batch_size=4,
                           shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(20))


def test_ndarray_iter_reset():
    X = np.arange(8, dtype=np.float32).reshape(4, 2)
    it = mx.io.NDArrayIter(X, np.zeros(4, np.float32), batch_size=2)
    n1 = len(list(it))
    it.reset()
    n2 = len(list(it))
    assert n1 == n2 == 2


def test_ndarray_iter_dict_data():
    it = mx.io.NDArrayIter({"a": np.zeros((6, 2), np.float32),
                            "b": np.ones((6, 3), np.float32)},
                           np.zeros(6, np.float32), batch_size=3)
    assert sorted(d.name for d in it.provide_data) == ["a", "b"]
    b0 = next(iter(it))
    assert len(b0.data) == 2


def test_resize_iter():
    X = np.zeros((12, 2), np.float32)
    base = mx.io.NDArrayIter(X, np.zeros(12, np.float32), batch_size=3)
    it = mx.io.ResizeIter(base, 2)
    assert len(list(it)) == 2


def test_prefetching_iter():
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = mx.io.NDArrayIter(X, np.zeros(12, np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 3
    assert_almost_equal(batches[0].data[0], X[:4])


def test_prefetching_iter_reset_and_depth():
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    base = mx.io.NDArrayIter(X, np.zeros(12, np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base, prefetch_depth=4)
    for _ in range(3):  # multiple epochs through reset
        batches = list(it)
        assert len(batches) == 3
        assert_almost_equal(batches[0].data[0], X[:4])
        it.reset()


def test_prefetching_iter_multi_source_rename():
    X1 = np.arange(16, dtype=np.float32).reshape(8, 2)
    X2 = np.arange(24, dtype=np.float32).reshape(8, 3)
    i1 = mx.io.NDArrayIter(X1, np.zeros(8, np.float32), batch_size=4)
    i2 = mx.io.NDArrayIter(X2, None, batch_size=4)
    it = mx.io.PrefetchingIter(
        [i1, i2], rename_data=[{"data": "d1"}, {"data": "d2"}],
        rename_label=[{"softmax_label": "l1"}, {}])
    names = [d.name for d in it.provide_data]
    assert names == ["d1", "d2"]
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 2)
    assert batches[0].data[1].shape == (4, 3)


def test_prefetching_iter_source_error_propagates():
    class Boom(mx.io.DataIter):
        def __init__(self):
            super().__init__(4)
            self.provide_data = [mx.io.DataDesc("data", (4, 2))]
            self.provide_label = []

        def reset(self):
            pass

        def next(self):
            raise ValueError("decode failed")

    it = mx.io.PrefetchingIter(Boom())
    try:
        it.next()
        assert False, "expected the source error to propagate"
    except ValueError as e:
        assert "decode failed" in str(e)


def test_csv_iter():
    with tempfile.TemporaryDirectory() as d:
        data_path = os.path.join(d, "data.csv")
        X = np.random.randn(10, 3).astype(np.float32)
        np.savetxt(data_path, X, delimiter=",")
        it = mx.io.CSVIter(data_csv=data_path, data_shape=(3,), batch_size=5)
        batches = list(it)
        assert len(batches) == 2
        assert_almost_equal(batches[0].data[0], X[:5], rtol=1e-4, atol=1e-5)


def test_recordio_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "test.rec")
        w = recordio.MXRecordIO(path, "w")
        records = [b"hello", b"world" * 100, b""]
        for r in records:
            w.write(r)
        w.close()
        r = recordio.MXRecordIO(path, "r")
        out = []
        while True:
            item = r.read()
            if item is None:
                break
            out.append(item)
        r.close()
    assert out == records


def test_indexed_recordio():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "test.rec")
        idx_path = os.path.join(d, "test.idx")
        w = recordio.MXIndexedRecordIO(idx_path, path, "w")
        for i in range(5):
            w.write_idx(i, b"rec%d" % i)
        w.close()
        r = recordio.MXIndexedRecordIO(idx_path, path, "r")
        assert r.read_idx(3) == b"rec3"
        assert r.read_idx(0) == b"rec0"
        r.close()


def test_indexed_recordio_missing_idx_closes_rec_handle():
    """When the sidecar .idx fails to open, the already-open .rec handle
    must be closed (ImageIter's remote-URI fallback constructs one of
    these per miss — it must not leak a handle each time)."""
    from mxnet_tpu import filesystem

    opened = []
    orig = filesystem.open_uri

    def tracking_open(uri, mode):
        h = orig(uri, mode)
        opened.append(h)
        return h

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "test.rec")
        w = recordio.MXRecordIO(path, "w")
        w.write(b"payload")
        w.close()
        filesystem.open_uri = tracking_open
        try:
            with pytest.raises(Exception):
                recordio.MXIndexedRecordIO(
                    os.path.join(d, "missing.idx"), path, "r")
        finally:
            filesystem.open_uri = orig
        assert len(opened) == 1  # the .rec opened, the .idx never did
        assert opened[0].closed


def test_irheader_pack_unpack():
    header = recordio.IRHeader(0, 2.0, 7, 0)
    data = b"imagebytes"
    packed = recordio.pack(header, data)
    h2, d2 = recordio.unpack(packed)
    assert h2.label == 2.0
    assert h2.id == 7
    assert d2 == data


def test_irheader_multi_label():
    header = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 1, 0)
    packed = recordio.pack(header, b"x")
    h2, d2 = recordio.unpack(packed)
    np.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])


def test_mnist_iter_synthetic():
    # MNISTIter reads idx-format files; synthesize a tiny one
    with tempfile.TemporaryDirectory() as d:
        img_path = os.path.join(d, "images-idx3-ubyte")
        lbl_path = os.path.join(d, "labels-idx1-ubyte")
        n = 20
        images = (np.random.rand(n, 28, 28) * 255).astype(np.uint8)
        labels = np.random.randint(0, 10, n).astype(np.uint8)
        import struct
        with open(img_path, "wb") as f:
            f.write(struct.pack(">IIII", 2051, n, 28, 28))
            f.write(images.tobytes())
        with open(lbl_path, "wb") as f:
            f.write(struct.pack(">II", 2049, n))
            f.write(labels.tobytes())
        it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                             shuffle=False)
        batches = list(it)
        assert len(batches) == 4
        b0 = batches[0]
        assert b0.data[0].shape[0] == 5
        np.testing.assert_allclose(b0.label[0].asnumpy(), labels[:5])


def test_prefetching_iter_next_after_exhaustion():
    # repeated next() past end-of-epoch must keep raising StopIteration
    # (not deadlock on dead worker queues)
    X = np.arange(8, dtype=np.float32).reshape(4, 2)
    base = mx.io.NDArrayIter(X, np.zeros(4, np.float32), batch_size=2)
    it = mx.io.PrefetchingIter(base)
    assert len(list(it)) == 2
    for _ in range(3):
        try:
            it.next()
            assert False, "expected StopIteration"
        except StopIteration:
            pass
    it.reset()
    assert len(list(it)) == 2


def test_symbol_grad_scale_roundtrip(tmp_path):
    # grad_scale is a declared op param and must survive save/load even
    # though graph-level scope attrs are filtered for extra-attrs ops
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax", grad_scale=2.0)
    p = str(tmp_path / "gs.json")
    net.save(p)
    loaded = mx.sym.load(p)
    node_attrs = [n for n in __import__("json").loads(loaded.tojson())["nodes"]
                  if n["name"] == "softmax"]
    assert node_attrs and float(
        node_attrs[0].get("attr", node_attrs[0].get("attrs", {}))
        .get("grad_scale", 1.0)) == 2.0

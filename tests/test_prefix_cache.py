"""Pool-level contract of the cross-request prefix cache: refcounted
copy-on-write sharing, the bounded LRU index, content-addressed
matching, and the fault hook that degrades lookups to misses.  Pure
host-side data-structure tests — no XLA, so they run in milliseconds.
Engine-level behavior (zero prefill steps, TTFT, speculative parity)
lives in test_generation.py."""
import numpy as np
import pytest

from mxnet_tpu import faults
from mxnet_tpu.generation import KVPoolExhaustedError, PagedKVPool


def _pool(num_pages=16, page_size=4, cache=8):
    return PagedKVPool(num_pages=num_pages, page_size=page_size,
                       num_layers=1, num_heads=2, head_dim=4,
                       prefix_cache_pages=cache)


def _publish(pool, sid, tokens, seed=0):
    """Alloc + write + register + free one transcript: its full pages
    stay behind in the index as refcount-0 cache."""
    rng = np.random.RandomState(seed)
    n = len(tokens)
    pool.alloc_prefix(sid, n, tokens=tokens)
    k = rng.randn(n, 2, 4).astype(np.float32)
    v = rng.randn(n, 2, 4).astype(np.float32)
    pool.write_prefill(sid, 0, k, v, n)
    pool.register_prefix(sid, tokens)
    pool.free(sid)


def test_hit_maps_shared_pages_and_refcounts_drain():
    pool = _pool()
    t = list(range(8))  # two full pages
    _publish(pool, "a", t)
    assert pool.cached_pages() == 2
    assert pool.live_pages() == 0  # cache pages are not "live"

    pages_b, cached_b = pool.alloc_prefix("b", 8, tokens=t)
    pages_c, cached_c = pool.alloc_prefix("c", 8, tokens=t)
    # both map the SAME physical pages, K/V already materialized
    assert cached_b == cached_c == 7  # final position always re-fed
    assert pages_b == pages_c
    assert pool.shared_pages() == 2
    assert pool.total_refcount() > 0
    pool.free("b")
    pool.free("c")
    assert pool.total_refcount() == 0
    assert pool.cached_pages() == 2  # retained for the NEXT request


def test_match_is_content_addressed_not_positional():
    pool = _pool()
    t = list(range(8))
    _publish(pool, "a", t)
    # same first page, different second page: one-page partial hit
    t2 = t[:4] + [99, 98, 97, 96]
    _, cached = pool.alloc_prefix("b", 8, tokens=t2)
    assert cached == 4
    # completely different content: clean miss
    _, cached = pool.alloc_prefix("c", 8, tokens=[50 + i for i in range(8)])
    assert cached == 0
    snap = pool.snapshot()
    assert snap["prefix_hits"] == 1 and snap["prefix_misses"] >= 1


def test_lru_index_is_bounded_and_counts_evictions():
    pool = _pool(num_pages=32, cache=3)
    for i in range(3):
        _publish(pool, "s%d" % i, [16 * i + j for j in range(8)], seed=i)
    # 3 transcripts x 2 full pages = 6 published, bound is 3
    assert pool.cached_pages() == 3
    snap = pool.snapshot()
    assert snap["prefix_evictions"] == 3
    assert snap["prefix_index_size"] == 3
    # the OLDEST transcript was evicted, the newest survives
    _, cached_old = pool.alloc_prefix("old", 8, tokens=[j for j in range(8)])
    assert cached_old == 0
    _, cached_new = pool.alloc_prefix("new", 8,
                                      tokens=[32 + j for j in range(8)])
    assert cached_new > 0


def test_allocation_pressure_reclaims_cache_but_never_shared_pages():
    pool = _pool(num_pages=8, cache=8)  # capacity 7
    t = list(range(8))
    _publish(pool, "a", t)  # 2 cached pages
    _, cached = pool.alloc_prefix("b", 8, tokens=t)  # maps both, refcount 1
    assert cached == 7
    # 5 pages left (7 - 2 shared); a 20-token alloc (5 pages) must evict
    # nothing shared — it fits exactly in the free remainder
    pool.alloc("fill", 20)
    assert pool.total_refcount() > 0  # b's shared mapping survived
    # now NOTHING is reclaimable: shared pages are pinned
    with pytest.raises(KVPoolExhaustedError):
        pool.alloc("overflow", 4)
    pool.free("b")
    pool.free("fill")


def test_cache_disabled_pool_never_retains():
    pool = _pool(cache=0)
    t = list(range(8))
    pool.alloc_prefix("a", 8, tokens=t)
    pool.register_prefix("a", t)
    pool.free("a")
    assert pool.cached_pages() == 0
    assert pool.free_pages() == pool.capacity
    _, cached = pool.alloc_prefix("b", 8, tokens=t)
    assert cached == 0


def test_occupancy_ratio_reaches_exactly_one():
    """Satellite regression: capacity excludes the reserved scratch
    page, so a full pool reads occupancy 1.0 — not the asymptote the
    raw num_pages denominator produced."""
    pool = _pool(num_pages=8, cache=0)
    assert pool.capacity == 7
    pool.alloc("a", 7 * 4)  # every allocatable page
    assert pool.occupancy() == 1.0
    assert pool.snapshot()["occupancy"] == 1.0


def test_lookup_fault_degrades_to_miss_not_failure():
    pool = _pool()
    t = list(range(8))
    _publish(pool, "a", t)
    with faults.inject("generation.prefix.lookup:ioerr=1", seed=0):
        pages, cached = pool.alloc_prefix("b", 8, tokens=t)
    assert cached == 0  # blinded lookup: full prefill, stream unharmed
    assert len(pages) == 2
    pool.free("b")
    # with the plan gone the same prompt hits again
    _, cached = pool.alloc_prefix("c", 8, tokens=t)
    assert cached == 7


def test_cow_split_preserves_digest_chain_for_future_hits():
    """After a COW split the writer owns a private copy; the original
    page keeps serving hits because digests are content-based."""
    pool = _pool()
    t = list(range(8))
    _publish(pool, "a", t)
    pages_b, _ = pool.alloc_prefix("b", 8, tokens=t)
    assert pool.is_shared("b", 7)
    assert pool.ensure_writable("b", 7)
    assert not pool.is_shared("b", 7)
    assert pool.snapshot()["cow_copies"] >= 1
    # a second ensure_writable is a no-op (already private)
    assert not pool.ensure_writable("b", 7)
    pages_c, cached = pool.alloc_prefix("c", 8, tokens=t)
    assert cached == 7
    assert pages_c[1] == pages_b[1]  # c maps the pre-split original
    pool.free("b")
    pool.free("c")
    assert pool.total_refcount() == 0

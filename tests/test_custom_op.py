"""Custom-op subsystem tests (reference: the custom softmax in
tests/python/unittest/test_operator.py and python/mxnet/operator.py:396-576)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


@mx.operator.register("_test_sigmoid")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super(SigmoidProp, self).__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class Sigmoid(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                y = 1.0 / (1.0 + np.exp(-x))
                self.assign(out_data[0], req[0], y.astype(x.dtype))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                y = out_data[0].asnumpy()
                g = out_grad[0].asnumpy()
                self.assign(in_grad[0], req[0],
                            (g * y * (1.0 - y)).astype(y.dtype))

        return Sigmoid()


@mx.operator.register("_test_softmax_loss")
class SoftmaxLossProp(mx.operator.CustomOpProp):
    """Reference-style custom softmax loss (need_top_grad=False)."""

    def __init__(self):
        super(SoftmaxLossProp, self).__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = [in_shape[0][0]]
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class SoftmaxLoss(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                e = np.exp(x - x.max(axis=1, keepdims=True))
                self.assign(out_data[0], req[0],
                            (e / e.sum(axis=1, keepdims=True)).astype(x.dtype))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                lab = in_data[1].asnumpy().astype(np.int64)
                y = out_data[0].asnumpy().copy()
                y[np.arange(lab.shape[0]), lab] -= 1.0
                self.assign(in_grad[0], req[0], y)
                self.assign(in_grad[1], req[1],
                            np.zeros_like(in_data[1].asnumpy()))

        return SoftmaxLoss()


def test_custom_imperative_forward():
    x = nd.array(np.array([[0.0, 1.0], [-1.0, 2.0]], np.float32))
    y = nd.Custom(x, op_type="_test_sigmoid")
    expect = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    assert_almost_equal(y.asnumpy(), expect, rtol=1e-5, atol=1e-6)


def test_custom_symbolic_forward_backward():
    data = sym.Variable("data")
    net = sym.Custom(data=data, op_type="_test_sigmoid", name="sig")
    xe = np.random.uniform(-2, 2, (4, 5)).astype(np.float32)
    exe = net.simple_bind(mx.cpu(), data=(4, 5), grad_req="write")
    exe.arg_dict["data"][:] = xe
    out = exe.forward(is_train=True)[0].asnumpy()
    expect = 1.0 / (1.0 + np.exp(-xe))
    assert_almost_equal(out, expect, rtol=1e-5, atol=1e-6)
    head = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    exe.backward(nd.array(head))
    grad = exe.grad_dict["data"].asnumpy()
    assert_almost_equal(grad, head * expect * (1 - expect),
                        rtol=1e-4, atol=1e-5)


def test_custom_softmax_trains():
    """End-to-end: a net with a custom softmax loss learns a separable toy
    problem (reference nightly gate style)."""
    np.random.seed(0)
    n, d, k = 128, 10, 3
    w_true = np.random.randn(d, k).astype(np.float32)
    x = np.random.randn(n, d).astype(np.float32)
    lab = (x @ w_true).argmax(axis=1).astype(np.float32)

    data = sym.Variable("data")
    label = sym.Variable("label")
    net = sym.FullyConnected(data=data, num_hidden=k, name="fc")
    net = sym.Custom(data=net, label=label, op_type="_test_softmax_loss",
                     name="loss")
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())
    it = mx.io.NDArrayIter(data=x, label=lab, batch_size=32, shuffle=True,
                           label_name="label")
    mod.fit(it, num_epoch=10,
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    mod.bind(data_shapes=[("data", (n, d))], label_shapes=[("label", (n,))],
             for_training=False, force_rebind=True)
    probs = mod.predict(mx.io.NDArrayIter(data=x, label=lab, batch_size=n,
                                          label_name="label")).asnumpy()
    acc = (probs.argmax(axis=1) == lab).mean()
    assert acc > 0.9, "custom softmax failed to train: acc=%.3f" % acc


def test_ndarray_op_legacy():
    class Square(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] * in_data[0]

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0] * in_data[0] * 2.0

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]]

    op = Square()
    data = sym.Variable("data")
    net = op.get_symbol(data, name="sq")
    xe = np.random.uniform(-2, 2, (3, 4)).astype(np.float32)
    exe = net.simple_bind(mx.cpu(), data=(3, 4), grad_req="write")
    exe.arg_dict["data"][:] = xe
    out = exe.forward(is_train=True)[0].asnumpy()
    assert_almost_equal(out, xe * xe, rtol=1e-5, atol=1e-6)
    exe.backward(nd.array(np.ones((3, 4), np.float32)))
    assert_almost_equal(exe.grad_dict["data"].asnumpy(), 2 * xe,
                        rtol=1e-4, atol=1e-5)


def test_python_op_legacy_numpy():
    class AddOne(mx.operator.PythonOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] + 1.0

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0]

    op = AddOne()
    data = sym.Variable("data")
    net = op.get_symbol(data, name="addone")
    exe = net.simple_bind(mx.cpu(), data=(2, 2), grad_req="write")
    exe.arg_dict["data"][:] = np.zeros((2, 2), np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out, np.ones((2, 2), np.float32))


@mx.operator.register("_test_scale")
class ScaleProp(mx.operator.CustomOpProp):
    def __init__(self, scale):
        super(ScaleProp, self).__init__(need_top_grad=True)
        self.scale = float(scale)

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        scale = self.scale

        class Scale(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0].asnumpy() * scale)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0], out_grad[0].asnumpy() * scale)

        return Scale()


def test_custom_json_round_trip():
    """Custom-op user kwargs must survive tojson/load_json (checkpointing)."""
    data = sym.Variable("data")
    net = sym.Custom(data=data, op_type="_test_scale", scale="3.0",
                     name="sc")
    js = net.tojson()
    loaded = mx.sym.load_json(js)
    x = np.random.uniform(-1, 1, (2, 3)).astype(np.float32)
    exe = loaded.simple_bind(mx.cpu(), data=(2, 3), grad_req="write")
    exe.arg_dict["data"][:] = x
    out = exe.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(out, x * 3.0, rtol=1e-5, atol=1e-6)


def test_custom_op_attrscope_json_roundtrip(tmp_path):
    # a Custom node built under AttrScope must survive save/load: scope
    # attrs (ctx_group, lr_mult) are graph-level, not constructor kwargs
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="dev1", lr_mult="0.5"):
        net = mx.sym.Custom(data, op_type="_test_sigmoid")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    path = str(tmp_path / "custom-attr.json")
    net.save(path)
    loaded = mx.sym.load(path)
    assert loaded.list_arguments() == net.list_arguments()
    # the scope attrs are preserved as node attrs after the round trip
    attrs = loaded.attr_dict()
    found = [v for k, v in attrs.items() if "ctx_group" in v]
    assert any(v.get("ctx_group") == "dev1" for v in found)


def test_custom_op_eager_no_callback(monkeypatch):
    # imperative mx.nd.Custom must not depend on jit host-callback support
    import mxnet_tpu.operator as op_mod

    called = {}

    def boom(*a, **k):
        called["hit"] = True
        raise AssertionError("pure_callback path used for eager Custom")

    monkeypatch.setattr(op_mod, "_custom_call", boom)
    x = mx.nd.array(np.array([[0.0, 1.0], [-1.0, 2.0]], np.float32))
    out = mx.nd.Custom(x, op_type="_test_sigmoid")
    expect = 1.0 / (1.0 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)
    assert "hit" not in called

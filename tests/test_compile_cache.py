"""Compile-cache tests — the PR-10 acceptance criteria as assertions.

Cross-process executable reuse (a second process starts warm: hits > 0,
zero compiles, bit-identical outputs), hot-swap under a warm cache (zero
cold-bucket runs, no new compiles), AOT bundle save/attach roundtrip with
a LOUD refusal on topology mismatch, version-mismatch invalidation as an
observable event, and — chaos-marked — corrupt/torn entries degrading to
a plain recompile with a structured telemetry event, never a crash.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile_cache as cc
from mxnet_tpu import faults, serving, telemetry
from mxnet_tpu.base import MXNetError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "compile_cache_worker.py")

IN_DIM = 6
HID = 3


def _reset():
    """Zero the counters AND drop the in-memory executable cache, so the
    next build must go through the disk (or an attached bundle)."""
    telemetry._reset_for_tests()
    cc.reset_stats()


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    """Fresh cache dir + clean instrument/memory state on both sides."""
    d = str(tmp_path / "cc")
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", d)
    _reset()
    yield d
    _reset()


def _tiny_model(seed=0):
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=HID,
                                name="fc")
    params = {
        "fc_weight": mx.nd.array(rng.randn(HID, IN_DIM).astype(np.float32)),
        "fc_bias": mx.nd.array(rng.randn(HID).astype(np.float32)),
    }
    return net, params


def _forward(net, params, X):
    pred = mx.Predictor(net, dict(params), {"data": X.shape})
    return pred.forward(data=X)[0].asnumpy()


def _run_worker(mode, cache_dir):
    env = dict(os.environ, MXNET_COMPILE_CACHE_DIR=cache_dir)
    proc = subprocess.run([sys.executable, WORKER, mode], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------------------------------------
# in-process roundtrip + keying
# ---------------------------------------------------------------------------

def test_predictor_roundtrip_in_process(cache_dir):
    """First build compiles and stores; after dropping the in-memory
    cache a fresh executor loads the disk entry — a hit, no compile —
    and produces bit-identical outputs."""
    net, params = _tiny_model()
    X = np.random.RandomState(3).randn(2, IN_DIM).astype(np.float32)
    out_cold = _forward(net, params, X)
    s = cc.stats()
    assert s["misses"] >= 1 and s["stores"] >= 1 and s["hits"] == 0
    assert cc.ls_entries(cache_dir), "store left no entry on disk"

    _reset()  # drops the in-memory executable cache: force disk
    out_warm = _forward(net, params, X)
    s = cc.stats()
    assert s["hits"] >= 1 and s["misses"] == 0 and s["errors"] == 0
    np.testing.assert_array_equal(out_cold, out_warm)


def test_signature_change_is_a_new_entry(cache_dir):
    """A different batch signature must not hit the old entry — the
    Compiled executable does not retrace on shape change, so serving it
    for the wrong shape would be a correctness bug."""
    net, params = _tiny_model()
    _forward(net, params, np.zeros((2, IN_DIM), np.float32))
    n1 = len(cc.ls_entries(cache_dir))
    _forward(net, params, np.zeros((4, IN_DIM), np.float32))
    n2 = len(cc.ls_entries(cache_dir))
    assert n2 > n1, "shape change reused the same cache entry"


def test_min_ms_threshold_skips_store(cache_dir, monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_MIN_MS", "1e9")
    net, params = _tiny_model()
    _forward(net, params, np.zeros((2, IN_DIM), np.float32))
    s = cc.stats()
    assert s["misses"] >= 1 and s["stores"] == 0
    assert not cc.ls_entries(cache_dir)


def test_version_mismatch_invalidates_with_event(cache_dir, monkeypatch):
    """An entry recorded under another jax version is a miss with a
    structured ``compile_cache_invalidate`` event — never served, never
    a crash."""
    net, params = _tiny_model()
    X = np.zeros((2, IN_DIM), np.float32)
    _forward(net, params, X)
    assert cc.stats()["stores"] >= 1
    _reset()
    telemetry.enable(trace=False)
    fake = dict(cc.env_fingerprint())
    fake["jax"] = "0.0.0-stale-test"
    monkeypatch.setattr(cc, "_env_fp_cache", fake)

    out = _forward(net, params, X)
    s = cc.stats()
    assert s["hits"] == 0 and s["misses"] >= 1 and s["errors"] == 0
    kinds = [e["kind"] for e in telemetry.events()]
    assert "compile_cache_invalidate" in kinds
    assert out.shape == (2, HID)


# ---------------------------------------------------------------------------
# cross-process reuse — the headline acceptance criterion
# ---------------------------------------------------------------------------

def test_cross_process_predictor_reuse(cache_dir):
    a = _run_worker("predict", cache_dir)
    assert a["stats"]["misses"] >= 1 and a["stats"]["stores"] >= 1

    b = _run_worker("predict", cache_dir)
    assert b["stats"]["hits"] >= 1, b["stats"]
    assert b["stats"]["misses"] == 0, \
        "second process ran the XLA compiler: %s" % b["stats"]
    assert b["stats"]["compile_ms"] == 0.0
    assert b["digest"] == a["digest"], \
        "cache-served outputs are not bit-identical"


@pytest.mark.slow
def test_cross_process_fused_train_reuse(cache_dir):
    """The fused train step (forward+backward+optimizer, donated) also
    roundtrips: the second process trains to bit-identical weights with
    zero compiles."""
    a = _run_worker("train", cache_dir)
    assert a["stats"]["misses"] >= 1 and a["stats"]["stores"] >= 1

    b = _run_worker("train", cache_dir)
    assert b["stats"]["hits"] >= 1 and b["stats"]["misses"] == 0, b["stats"]
    assert b["digest"] == a["digest"], \
        "warm-start training diverged from the cold-start run"


# ---------------------------------------------------------------------------
# serving: warm swap + AOT bundles
# ---------------------------------------------------------------------------

def test_hot_swap_warm_cache_zero_compiles(cache_dir, tmp_path):
    """swap() under a warm cache: the shadow replica's full warmup is
    served from cache — no cold-bucket runs, no new compiles, and the
    post-swap outputs carry the NEW params (the executable is reused,
    the weights are not baked in)."""
    net, params1 = _tiny_model(seed=12)
    _, params2 = _tiny_model(seed=13)
    prefix = str(tmp_path / "swapcc")
    mx.model.save_checkpoint(prefix, 1, net, dict(params1), {})
    mx.model.save_checkpoint(prefix, 2, net, dict(params2), {})
    X = np.random.RandomState(8).randn(4, IN_DIM).astype(np.float32)

    srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
    try:
        before = cc.stats()
        assert before["misses"] >= 1  # initial warmup did compile
        srv.swap(prefix, 2)
        after = cc.stats()
        assert srv.cold_bucket_runs() == 0
        assert after["misses"] == before["misses"], \
            "swap shadow recompiled instead of inheriting executables"
        assert after["compile_ms"] == before["compile_ms"]
        assert after["hits"] > before["hits"]
        ref2 = _forward(net, params2, X[:1])
        np.testing.assert_allclose(srv.predict(data=X[0])[0], ref2[0],
                                   rtol=1e-5, atol=1e-6)
    finally:
        srv.stop()


def test_aot_bundle_roundtrip(cache_dir, tmp_path, monkeypatch):
    """save_aot_bundle beside the checkpoint, then restore with NO cache
    dir configured: from_checkpoint auto-attaches the bundle and the
    whole warmup is deserialize-only."""
    net, params = _tiny_model(seed=4)
    prefix = str(tmp_path / "aot")
    mx.model.save_checkpoint(prefix, 1, net, dict(params), {})
    X = np.random.RandomState(5).randn(4, IN_DIM).astype(np.float32)

    srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
    try:
        ref = srv.predict(data=X[0])[0]
        bundle = srv.save_aot_bundle(prefix, 1)
    finally:
        srv.stop()
    manifest = cc.read_manifest(bundle)
    assert manifest["entries"], "bundle saved no executables"
    assert manifest["warmup"]["buckets"]

    _reset()  # also detaches bundles + drops the memory cache
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", "")
    srv2 = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
    try:
        s = cc.stats()
        assert s["hits"] >= 1 and s["misses"] == 0, \
            "bundle-attached warmup still compiled: %s" % s
        np.testing.assert_array_equal(srv2.predict(data=X[0])[0], ref)
    finally:
        srv2.stop()


def test_aot_bundle_topology_mismatch_refused(cache_dir, tmp_path):
    """A bundle built for a different device topology must be refused
    loudly at attach time, and attach_aot=False must still serve."""
    net, params = _tiny_model(seed=4)
    prefix = str(tmp_path / "aotbad")
    mx.model.save_checkpoint(prefix, 1, net, dict(params), {})
    srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
    try:
        bundle = srv.save_aot_bundle(prefix, 1)
    finally:
        srv.stop()
    mpath = os.path.join(bundle, cc.MANIFEST_NAME)
    manifest = cc.read_manifest(bundle)
    manifest["env"]["device_count"] = manifest["env"]["device_count"] + 8
    with open(mpath, "w") as f:
        json.dump(manifest, f)

    _reset()
    with pytest.raises(MXNetError, match="device_count"):
        serving.InferenceServer.from_checkpoint(
            prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
    srv3 = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, attach_aot=False,
        max_wait_us=1000)
    srv3.stop()


# ---------------------------------------------------------------------------
# admin surface
# ---------------------------------------------------------------------------

def test_admin_ls_verify_prune(cache_dir):
    net, params = _tiny_model()
    _forward(net, params, np.zeros((2, IN_DIM), np.float32))
    _forward(net, params, np.zeros((4, IN_DIM), np.float32))
    entries = cc.ls_entries(cache_dir)
    assert len(entries) >= 2
    assert all(e["env_ok"] for e in entries)
    for e in entries:
        ok, detail = cc.verify_entry(e["path"])
        assert ok, detail

    # budget 0 MB: prune removes everything, oldest first
    removed = cc.prune(cache_dir, 0)
    assert sorted(removed) == sorted(e["path"] for e in entries)
    assert not cc.ls_entries(cache_dir)
    assert not [n for n in os.listdir(cache_dir) if n.endswith(".crc32")]


def test_admin_cli_verify_flags_corruption(cache_dir):
    net, params = _tiny_model()
    _forward(net, params, np.zeros((2, IN_DIM), np.float32))
    entry = cc.ls_entries(cache_dir)[0]["path"]
    tool = os.path.join(ROOT, "tools", "compile_cache_admin.py")
    env = dict(os.environ, MXNET_COMPILE_CACHE_DIR=cache_dir)

    proc = subprocess.run(
        [sys.executable, tool, "verify", "--dir", cache_dir, "--json"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert json.loads(proc.stdout)["bad"] == 0

    with open(entry, "r+b") as f:  # flip one payload byte
        f.seek(-1, os.SEEK_END)
        byte = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([byte[0] ^ 0xFF]))
    proc = subprocess.run(
        [sys.executable, tool, "verify", "--dir", cache_dir, "--json"],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert json.loads(proc.stdout)["bad"] >= 1


# ---------------------------------------------------------------------------
# chaos: corruption and injected I/O faults degrade to recompiles
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_corrupt_entry_degrades_to_recompile(cache_dir):
    net, params = _tiny_model()
    X = np.random.RandomState(9).randn(2, IN_DIM).astype(np.float32)
    out_cold = _forward(net, params, X)
    entry = cc.ls_entries(cache_dir)[0]["path"]
    with open(entry, "r+b") as f:  # corrupt the payload: CRC must catch it
        f.seek(-4, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")

    _reset()
    telemetry.enable(trace=False)
    out = _forward(net, params, X)
    s = cc.stats()
    assert s["errors"] >= 1, "corruption went unnoticed"
    assert s["misses"] >= 1 and s["hits"] == 0
    np.testing.assert_array_equal(out, out_cold)
    kinds = [e["kind"] for e in telemetry.events()]
    assert "compile_cache_corrupt" in kinds


@pytest.mark.chaos
def test_injected_load_ioerr_degrades(cache_dir):
    net, params = _tiny_model()
    X = np.zeros((2, IN_DIM), np.float32)
    out_cold = _forward(net, params, X)
    _reset()
    with faults.inject("compile_cache.load:ioerr=1") as plan:
        out = _forward(net, params, X)
        assert ("compile_cache.load", "ioerr", 1) in plan.events
    s = cc.stats()
    assert s["errors"] >= 1 and s["misses"] >= 1 and s["hits"] == 0
    np.testing.assert_array_equal(out, out_cold)


@pytest.mark.chaos
def test_torn_store_never_leaves_partial_entry(cache_dir):
    """A torn write mid-store (injected partial) must leave NO entry file
    behind (atomic_write tears the temp, not the target) and the build
    itself still succeeds — store failure is an error counter, not an
    exception."""
    net, params = _tiny_model()
    with faults.inject("compile_cache.store:partial=1@0.5"):
        out = _forward(net, params, np.zeros((2, IN_DIM), np.float32))
    assert out.shape == (2, HID)
    s = cc.stats()
    assert s["errors"] >= 1 and s["stores"] == 0
    assert not cc.ls_entries(cache_dir)
    leftovers = [n for n in os.listdir(cache_dir)
                 if n.endswith(cc.ENTRY_SUFFIX)] \
        if os.path.isdir(cache_dir) else []
    assert not leftovers

    # the NEXT store (fault cleared) repopulates the cache cleanly
    _forward(net, params, np.zeros((4, IN_DIM), np.float32))
    assert cc.stats()["stores"] >= 1


@pytest.mark.chaos
def test_strict_mode_raises_on_corrupt(cache_dir, monkeypatch):
    net, params = _tiny_model()
    _forward(net, params, np.zeros((2, IN_DIM), np.float32))
    entry = cc.ls_entries(cache_dir)[0]["path"]
    with open(entry, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")
    _reset()
    monkeypatch.setenv("MXNET_COMPILE_CACHE_STRICT", "1")
    with pytest.raises(Exception):
        _forward(net, params, np.zeros((2, IN_DIM), np.float32))

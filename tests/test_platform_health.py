"""Failure-domain platform tests — health plane debounce, domain-aware
replica spread, the degradation ladder (reap / warm re-fault / brownout /
graceful page-out), leak-free fault-in failure, fault-in-window 503s, and
concurrent page-out vs in-flight traffic.  All CPU-only with tiny
explicit pools; host death is simulated by stopping heartbeats (TTL
eviction) or injected probe faults — never by real process kills."""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, serving, telemetry
from mxnet_tpu.platform import (BrownoutError, DevicePool,
                                FaultInProgressError, FrontDoor,
                                HealthPlane, ModelManager, ModelSpec,
                                PlacementPlanner)
from mxnet_tpu.serving.batcher import ServerClosedError
from mxnet_tpu.serving.registry import ReplicaRegistry
from mxnet_tpu.serving.router import NoReplicaAvailableError, Router

IN_DIM = 4


@pytest.fixture(autouse=True)
def _platform_env(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    monkeypatch.setenv("MXNET_PLATFORM_MIN_RESIDENT_S", "0")
    telemetry._reset_for_tests()
    yield
    telemetry._reset_for_tests()


def _save_fc(tmp_path, name, seed=0, in_dim=IN_DIM, hid=2):
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=hid,
                                name="fc")
    params = {
        "fc_weight": mx.nd.array(rng.randn(hid, in_dim).astype(np.float32)),
        "fc_bias": mx.nd.array(rng.randn(hid).astype(np.float32)),
    }
    prefix = str(tmp_path / name)
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    return prefix, {"data": (1, in_dim)}


def _fc_spec(tmp_path, name, **kw):
    prefix, shapes = _save_fc(tmp_path, name, seed=sum(map(ord, name)) % 97)
    kw.setdefault("param_bytes", 1000)
    kw.setdefault("server_kwargs", {"buckets": (1,)})
    return ModelSpec(name, prefix, 1, shapes, **kw)


def _spec(name, pbytes=100, **kw):
    return ModelSpec(name, "/nonexistent/%s" % name, 1,
                     {"data": (1, IN_DIM)}, param_bytes=pbytes, **kw)


def _tiny_server(seed=0):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="fc")
    rng = np.random.RandomState(seed)
    params = {"fc_weight": mx.nd.array(rng.randn(2, IN_DIM)
                                       .astype(np.float32)),
              "fc_bias": mx.nd.array(rng.randn(2).astype(np.float32))}
    return serving.InferenceServer(net, params, {"data": (1, IN_DIM)},
                                   buckets=(1,), warmup=False)


# -- device pool domains -----------------------------------------------------

def test_pool_failure_domains():
    pool = DevicePool(num_devices=5, bytes_per_device=100,
                      devices_per_host=2)
    assert pool.num_domains == 3
    assert [pool.domain_of(d) for d in range(5)] == [0, 0, 1, 1, 2]
    assert pool.devices_in(1) == [2, 3]
    assert pool.devices_in(2) == [4]  # ragged last host
    # default: one domain holds everything
    assert DevicePool(num_devices=4, bytes_per_device=1).num_domains == 1


# -- health plane ------------------------------------------------------------

def test_healthplane_registry_debounce_and_recovery():
    """A dead host does not deregister — its heartbeats stop and TTL
    eviction empties its domain.  K consecutive empty probes flip the
    domain down; recovery needs positive heartbeat evidence.  A domain
    that never held replicas is idle, not dead."""
    pool = DevicePool(num_devices=2, bytes_per_device=100,
                      devices_per_host=1)
    reg = ReplicaRegistry(ttl_ms=80)
    srv = _tiny_server()
    seen = []
    hp = HealthPlane(pool, registry=reg, probe_fails=2,
                     on_change=lambda d, up: seen.append((d, up)))
    try:
        reg.register("m/r1", srv, meta={"model": "m", "device": 0})
        assert hp.probe() == []
        assert hp.alive_devices() == [0, 1]

        # heartbeats stop; the entry TTL-evicts; two misses flip dom 0
        time.sleep(0.12)
        assert hp.probe() == []  # miss 1: debounced
        assert hp.probe() == [(0, False)]
        assert hp.dead_domains() == [0]
        assert hp.alive_devices() == [1]  # dom 1 never had replicas: idle
        assert not hp.is_alive(0) and hp.is_alive(1)
        assert hp.probe() == []  # still down, no flapping

        # recovery requires a replica heartbeating from the domain again
        reg.register("m/r2", srv, meta={"model": "m", "device": 0})
        assert hp.probe() == [(0, True)]
        assert hp.alive_devices() == [0, 1]
        assert seen == [(0, False), (0, True)]
    finally:
        reg.close()
        srv.stop(drain=False)


def test_healthplane_fault_injected_domain_kill_and_marks():
    pool = DevicePool(num_devices=4, bytes_per_device=100,
                      devices_per_host=2)
    hp = HealthPlane(pool, probe_fails=1)
    with faults.inject("platform.health.domain.1:ioerr=1", seed=7):
        assert hp.probe() == [(1, False)]
    assert hp.alive_devices() == [0, 1]
    # without a registry, a clean sweep is recovery evidence enough
    assert hp.probe() == [(1, True)]
    hp.mark_down(0)
    assert hp.dead_domains() == [0]
    hp.mark_up(0)
    assert hp.dead_domains() == []
    assert hp.describe()["domains"][0]["alive"]


# -- planner: replica spread + dead capacity ---------------------------------

def test_planner_spreads_replicas_across_domains():
    pool = DevicePool(num_devices=4, bytes_per_device=300,
                      devices_per_host=2)
    specs = {"m": _spec("m", pbytes=160, replicas=2)}  # total 200
    plan = PlacementPlanner(pool).plan(specs, {"m": 1.0})
    placed = plan.replica_devices["m"]
    assert len(placed) == 2
    doms = {pool.domain_of(d) for d in placed.values()}
    assert doms == {0, 1}  # one host lost => one replica lost, not both
    assert all("replica" in a for a in plan.actions)
    # both replicas fit one host when the other is dead: capacity over
    # availability once there is nothing left to spread across
    plan = PlacementPlanner(pool).plan(specs, {"m": 1.0},
                                       alive_devices=[0, 1])
    placed = plan.replica_devices["m"]
    assert len(placed) == 2
    assert {pool.domain_of(d) for d in placed.values()} == {0}


def test_planner_excludes_dead_devices_and_migrates_off_them():
    pool = DevicePool(num_devices=2, bytes_per_device=300,
                      devices_per_host=1)
    specs = {"a": _spec("a", pbytes=160)}
    # 'a' sits on device 0; host 0 dies; the plan moves it to device 1
    plan = PlacementPlanner(pool).plan(specs, {"a": 1.0}, current={"a": 0},
                                       alive_devices=[1])
    assert plan.resident == {"a": 1}
    assert {"op": "migrate", "model": "a", "src": 0, "dst": 1} \
        in plan.actions
    # nothing alive: everything is planned paged
    plan = PlacementPlanner(pool).plan(specs, {"a": 1.0}, current={"a": 0},
                                       alive_devices=[])
    assert plan.paged == ["a"]


# -- manager: multi-replica lifecycle ----------------------------------------

def test_manager_two_replicas_and_selective_page_out(tmp_path):
    pool = DevicePool(num_devices=2, bytes_per_device=1 << 20,
                      devices_per_host=1)
    with ModelManager(pool) as mgr:
        mgr.register_model(_fc_spec(tmp_path, "dup", replicas=2))
        s0 = mgr.fault_in("dup", 0, replica=0)
        s1 = mgr.fault_in("dup", 1, replica=1)
        assert s0 is not s1
        assert mgr.replica_placement() == {"dup": {0: 0, 1: 1}}
        assert mgr.placement() == {"dup": 0}  # primary view
        metas = mgr.registry.live()["meta"]
        assert {m["replica"] for m in metas.values()} == {0, 1}
        assert {m["device"] for m in metas.values()} == {0, 1}

        mgr.page_out("dup", replica=1)
        assert mgr.replica_placement() == {"dup": {0: 0}}
        assert mgr.server_for("dup") is s0
        assert len(mgr.registry.live()["replicas"]) == 1

        # the survivor keeps serving; a full page-out clears everything
        s0.submit(data=np.zeros(IN_DIM, np.float32)).result()
        mgr.page_out("dup")
        assert mgr.resident_bytes() == 0
        assert mgr.server_for("dup") is None


def test_manager_kill_replica_leaves_control_plane_stale(tmp_path,
                                                         monkeypatch):
    """kill_replica is a dead host: serving stops, heartbeats stop, but
    the manager still believes the replica is placed until the health
    plane reaps it — exactly the window the ladder closes."""
    # beats faster than the TTL, so only the CORPSE evicts
    monkeypatch.setenv("MXNET_SERVING_REGISTRY_HEARTBEAT_MS", "20")
    pool = DevicePool(num_devices=2, bytes_per_device=1 << 20,
                      devices_per_host=1)
    reg = ReplicaRegistry(ttl_ms=150)
    with ModelManager(pool, registry=reg) as mgr:
        mgr.register_model(_fc_spec(tmp_path, "vic", replicas=2))
        mgr.fault_in("vic", 0, replica=0)
        s1 = mgr.fault_in("vic", 1, replica=1)
        assert mgr.kill_replica("vic", replica=0)
        assert not mgr.kill_replica("ghost")  # unknown: False, no raise
        # control plane still lists both replicas...
        assert mgr.replica_placement() == {"vic": {0: 0, 1: 1}}
        # ...but server_for skips the corpse
        assert mgr.server_for("vic") is s1
        # and the corpse's registry entry TTL-evicts (no deregister)
        time.sleep(0.25)
        assert set(reg.live()["replicas"]) == {"vic/r2"}


# -- satellite 2: fault-in failure leaks nothing -----------------------------

def test_fault_in_failure_releases_partial_allocation(tmp_path):
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr:
        mgr.register_model(_fc_spec(tmp_path, "torn"))
        baseline = mgr.resident_bytes()
        # warmup fires AFTER params land on device: the worst leak path
        with faults.inject("serving.server.warmup:ioerr=1", seed=3):
            with pytest.raises(OSError):
                mgr.fault_in("torn")
        assert mgr.resident_bytes() == baseline
        assert mgr.server_for("torn") is None
        assert mgr.fault_in_window("torn") is None  # window closed
        assert mgr.registry.live()["replicas"] == {}
        # torn AOT bundle read (the ISSUE's named injection point)
        with faults.inject("checkpoint.aot.attach:ioerr=1", seed=3):
            with pytest.raises(OSError):
                mgr.fault_in("torn")
        assert mgr.resident_bytes() == baseline
        # the retry succeeds and serves
        srv = mgr.fault_in("torn")
        srv.submit(data=np.zeros(IN_DIM, np.float32)).result()
        assert mgr.resident_bytes() > baseline
    text = telemetry.render_prometheus()
    assert 'mxtpu_platform_fault_in_failures_total{model="torn"} 2' in text


# -- satellite 1: 503 + Retry-After during the fault-in window ---------------

def test_frontdoor_rejects_during_fault_in_window(tmp_path):
    telemetry.enable()
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr, FrontDoor(mgr) as door:
        mgr.register_model(_fc_spec(tmp_path, "slowm"))
        errs = []

        def owner():
            try:
                with faults.inject("platform.fault_in:delay=1@0.6", seed=1):
                    mgr.fault_in("slowm")
            except Exception as exc:  # pragma: no cover
                errs.append(exc)

        t = threading.Thread(target=owner)
        t.start()
        try:
            deadline = time.monotonic() + 5.0
            while mgr.fault_in_window("slowm") is None:
                assert time.monotonic() < deadline, "window never opened"
                time.sleep(0.005)
            with pytest.raises(FaultInProgressError) as ei:
                door.predict("slowm", data=np.zeros(IN_DIM, np.float32))
            assert ei.value.retry_after > 0
        finally:
            t.join()
        assert not errs
        assert mgr.fault_in_window("slowm") is None
        # after the window closes, the same request serves normally
        out = door.predict("slowm", data=np.zeros(IN_DIM, np.float32))
        assert np.asarray(out[0]).shape == (2,)
        evs = telemetry.events_of("platform_faultin_wait")
        assert [e["decision"] for e in evs] == ["rejected"]
        assert evs[0]["retry_after"] > 0 and "gen" in evs[0]


# -- the degradation ladder --------------------------------------------------

def test_degradation_ladder_brownout_and_recovery(tmp_path):
    """Host loss with two single-device hosts: the interactive model is
    re-faulted warm onto the survivor (rung 1), the batch model is paged
    out (rung 3), and the door browns out the batch class (rung 2) until
    the host returns."""
    telemetry.enable()
    pool = DevicePool(num_devices=2, bytes_per_device=1300,
                      devices_per_host=1)
    reg = ReplicaRegistry(ttl_ms=60_000)
    with ModelManager(pool, registry=reg) as mgr, FrontDoor(mgr) as door:
        hp = mgr.attach_health(HealthPlane(pool, registry=reg,
                                           probe_fails=1))
        mgr.register_model(_fc_spec(tmp_path, "gold", slo="interactive",
                                    tenant="gold"))
        mgr.register_model(_fc_spec(tmp_path, "bulk", slo="batch",
                                    tenant="bulk"))
        mgr.record_demand("gold", 5)
        mgr.record_demand("bulk", 1)
        mgr.replan()
        assert mgr.placement() == {"gold": 0, "bulk": 1}
        gen0 = mgr.plan_generation()

        # host 0 dies: gold's replica is killed, the probe notices
        mgr.kill_replica("gold")
        hp.mark_down(0)  # explicit transition -> ladder fires inline

        assert mgr.plan_generation() > gen0
        assert mgr.placement() == {"gold": 1}  # rung 1: warm re-fault
        assert mgr.server_for("gold").cold_bucket_runs() == 0
        assert mgr.server_for("bulk") is None  # rung 3: paged out
        b = door.quotas.brownout()
        assert b is not None and b[0] == 1  # rung 2: floor below batch

        # interactive traffic keeps its SLO; batch is shed with an ETA
        out = door.predict("gold", tenant="gold",
                           data=np.zeros(IN_DIM, np.float32))
        assert np.asarray(out[0]).shape == (2,)
        with pytest.raises(BrownoutError) as ei:
            door.predict("bulk", tenant="bulk", slo="batch",
                         data=np.zeros(IN_DIM, np.float32))
        assert ei.value.retry_after > 0
        assert door.quotas.snapshot()["bulk"]["browned"] == 1

        # the host comes back: replan restores bulk, brownout lifts
        hp.mark_up(0)
        assert door.quotas.brownout() is None
        assert mgr.server_for("bulk") is not None
        out = door.predict("bulk", tenant="bulk", slo="batch",
                           data=np.zeros(IN_DIM, np.float32))
        assert np.asarray(out[0]).shape == (2,)

        reaps = telemetry.events_of("platform_replica_reap")
        assert [(e["model"], e["domain"]) for e in reaps] == [("gold", 0)]
        b_evs = telemetry.events_of("platform_brownout")
        assert [e["engaged"] for e in b_evs] == [True, False]
        gens = [e["gen"] for e in telemetry.events_of(
            "platform_plan_actuate")]
        assert gens == sorted(gens)  # monotonic plan generations
    reg.close()


# -- satellite 4: concurrent page-out vs in-flight traffic -------------------

def test_concurrent_page_out_vs_inflight_infer(tmp_path):
    """Predict storms race a graceful page-out: every request either
    completes or fails with the retryable family — never a hang, never a
    partial-state crash — and the model demand-pages back in warm."""
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr, FrontDoor(mgr) as door:
        mgr.register_model(_fc_spec(tmp_path, "race"))
        door.predict("race", data=np.zeros(IN_DIM, np.float32))
        stop = threading.Event()
        oks, fails, bad = [0], [0], []

        def storm():
            while not stop.is_set():
                try:
                    door.predict("race",
                                 data=np.zeros(IN_DIM, np.float32))
                    oks[0] += 1
                except (ServerClosedError, NoReplicaAvailableError,
                        FaultInProgressError):
                    fails[0] += 1
                except Exception as exc:  # pragma: no cover
                    bad.append(exc)
                    return

        threads = [threading.Thread(target=storm) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(3):
                time.sleep(0.05)
                mgr.page_out("race", graceful=True)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not any(t.is_alive() for t in threads)
        assert not bad, bad
        assert oks[0] > 0
        # post-race state is consistent: one more request re-faults warm
        out = door.predict("race", data=np.zeros(IN_DIM, np.float32))
        assert np.asarray(out[0]).shape == (2,)
        assert mgr.server_for("race").cold_bucket_runs() == 0


def test_concurrent_page_out_vs_inflight_generate(tmp_path):
    """A live generate stream races a graceful page-out of its only
    replica: the stream either finishes or surfaces the retryable
    family (with a second replica the router resumes it — that path is
    the chaos host-loss scenario's job)."""
    V, S = 16, 16
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=1,
                                       num_heads=2, hidden=16, seq_len=S)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(2)
    params = {n: mx.nd.array(rng.randn(*s).astype(np.float32) * 0.05)
              for n, s in zip(net.list_arguments(), arg_shapes)
              if n not in ("data", "softmax_label")}
    prefix = str(tmp_path / "lm")
    mx.model.save_checkpoint(prefix, 1, net, params, {})
    gspec = dict(vocab_size=V, num_layers=1, num_heads=2, hidden=16,
                 max_seq_len=S, lane_buckets=(1,), page_size=4,
                 num_pages=16, prefill_len_buckets=(8,),
                 prefill_batch_buckets=(1,))
    pool = DevicePool(num_devices=1, bytes_per_device=1 << 20)
    with ModelManager(pool) as mgr, FrontDoor(mgr) as door:
        mgr.register_model(ModelSpec(
            "lm", prefix, 1, {"data": (1, S), "softmax_label": (1, S)},
            slo="generate", generator_spec=gspec,
            server_kwargs={"buckets": (1,)}))
        # a full, unraced stream works
        assert len(list(door.generate("lm", [3, 1, 4], 4))) == 4

        done = threading.Event()
        bad = []

        def streamer():
            try:
                for _ in range(20):
                    list(door.generate("lm", [3, 1, 4], 8))
            except (ServerClosedError, NoReplicaAvailableError,
                    FaultInProgressError):
                pass
            except Exception as exc:  # pragma: no cover
                bad.append(exc)
            finally:
                done.set()

        t = threading.Thread(target=streamer)
        t.start()
        time.sleep(0.05)
        mgr.page_out("lm", graceful=True)
        assert done.wait(timeout=60), "stream hung across page-out"
        t.join(timeout=5)
        assert not bad, bad
        # and the model comes back warm
        assert len(list(door.generate("lm", [3, 1, 4], 4))) == 4


# -- satellite 3: router probe debounce knob ---------------------------------

def test_router_probe_fails_env(monkeypatch):
    from mxnet_tpu.serving.router import _RemoteReplica

    reg = ReplicaRegistry(ttl_ms=60_000)
    r = Router(registry=reg, registry_sync_ms=10_000)
    try:
        rep = _RemoteReplica("a", "http://127.0.0.1:9", r)
        assert rep._probe_k == 3  # MXNET_SERVING_PROBE_FAILURES default
        monkeypatch.setenv("MXNET_ROUTER_PROBE_FAILS", "1")
        assert _RemoteReplica("b", "http://127.0.0.1:9", r)._probe_k == 1
        monkeypatch.setenv("MXNET_ROUTER_PROBE_FAILS", "0")
        monkeypatch.setenv("MXNET_SERVING_PROBE_FAILURES", "5")
        assert _RemoteReplica("c", "http://127.0.0.1:9", r)._probe_k == 5
    finally:
        r.close()
        reg.close()

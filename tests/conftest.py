"""Test config: run on a virtual 8-device CPU mesh so sharding/DP paths are
exercised without TPU hardware (reference analogue: test_multi_device_exec.py
faking group2ctx with multiple mx.cpu(i) contexts)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# the harness environment presets the axon TPU platform (and something in the
# image pins jax_platforms to "axon,cpu" ignoring the env var); tests run on
# the virtual 8-device CPU platform, so force the config before backend init
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)

"""Image pipeline tests: mx.image functions/augmenters, ImageIter,
ImageRecordIter, and the im2rec packer round-trip (reference:
tests/python/unittest/test_io.py + test_recordio.py + image.py usage)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, image_backend, nd, recordio

pytestmark = pytest.mark.skipif(not image_backend.HAVE_PIL,
                                reason="PIL not available")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IM2REC = os.path.join(REPO_ROOT, "tools", "im2rec.py")


def _im2rec(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.check_call([sys.executable, IM2REC] + list(args),
                          cwd=REPO_ROOT, env=env)


def _make_img(h, w, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 255, (h, w, 3), dtype=np.uint8)


def _make_dataset(tmp_path, n=12, size=32):
    """Write n PNGs in two class subdirs; return root."""
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        os.makedirs(root / cls, exist_ok=True)
    for i in range(n):
        cls = "cat" if i % 2 == 0 else "dog"
        buf = image_backend.encode_image(_make_img(size, size, seed=i),
                                         ".png")
        with open(root / cls / ("im%03d.png" % i), "wb") as f:
            f.write(buf)
    return str(root)


def test_imdecode_imresize_round_trip():
    img = _make_img(24, 16)
    buf = image_backend.encode_image(img, ".png")
    dec = image.imdecode(buf)
    assert dec.shape == (24, 16, 3)
    assert np.array_equal(dec.asnumpy(), img)  # png is lossless
    r = image.imresize(dec, 8, 12)
    assert r.shape == (12, 8, 3)


def test_nd_imdecode_batch_out_slice():
    """nd.imdecode(out=4-D, index=i) fills ONLY slice i (reference
    ndarray.cc Imdecode: ret->Slice(index, index+1))."""
    img = _make_img(6, 5)
    buf = image_backend.encode_image(img, ".png")
    out = nd.zeros((3, 3, 6, 5))
    nd.imdecode(buf, out=out, index=1)
    got = out.asnumpy()
    chw = img.transpose(2, 0, 1).astype(np.float32)
    np.testing.assert_allclose(got[1], chw)
    assert not got[0].any() and not got[2].any()


def test_cv_ops_imperative():
    img = _make_img(10, 10)
    buf = np.frombuffer(image_backend.encode_image(img, ".png"), np.uint8)
    dec = nd._cvimdecode(nd.array(buf, dtype=np.uint8))
    assert dec.shape == (10, 10, 3)
    res = nd._cvimresize(dec, w=5, h=7)
    assert res.shape == (7, 5, 3)
    pad = nd._cvcopyMakeBorder(dec, top=1, bot=2, left=3, right=4)
    assert pad.shape == (13, 17, 3)


def test_crops_and_normalize():
    img = nd.array(_make_img(40, 30))
    c, _ = image.center_crop(img, (20, 20))
    assert c.shape == (20, 20, 3)
    r, (x0, y0, w, h) = image.random_crop(img, (16, 16))
    assert r.shape == (16, 16, 3) and w == 16 and h == 16
    s = image.resize_short(img, 24)
    assert min(s.shape[:2]) == 24
    norm = image.color_normalize(nd.array(_make_img(4, 4).astype(np.float32)),
                                 mean=np.array([1.0, 2.0, 3.0]),
                                 std=np.array([2.0, 2.0, 2.0]))
    assert norm.dtype == np.float32


def test_augmenter_chain_shapes():
    auglist = image.CreateAugmenter((3, 20, 20), resize=24, rand_crop=True,
                                    rand_mirror=True, mean=True, std=True,
                                    brightness=0.1, contrast=0.1,
                                    saturation=0.1, pca_noise=0.05)
    arr = nd.array(_make_img(40, 32))
    for aug in auglist:
        arr = aug(arr)[0]
    out = arr.asnumpy()
    assert out.shape == (20, 20, 3)
    assert out.dtype == np.float32


def test_im2rec_pack_and_image_iter(tmp_path):
    root = _make_dataset(tmp_path)
    prefix = str(tmp_path / "data")
    _im2rec("--list", "--recursive", prefix, root)
    assert os.path.exists(prefix + ".lst")
    _im2rec("--recursive", prefix, root)
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")

    it = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                         path_imgrec=prefix + ".rec",
                         aug_list=image.CreateAugmenter((3, 24, 24),
                                                        resize=28))
    batches = list(it)
    assert len(batches) == 3  # 12 imgs / 4
    for b in batches:
        assert b.data[0].shape == (4, 3, 24, 24)
        assert b.label[0].shape == (4,)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert set(labels.astype(int)) == {0, 1}
    # reset and re-iterate
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter_threaded(tmp_path):
    root = _make_dataset(tmp_path, n=16, size=40)
    prefix = str(tmp_path / "rec2")
    _im2rec("--list", "--recursive", prefix, root)
    _im2rec("--recursive", prefix, root)
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 32, 32), batch_size=8,
                               resize=36, rand_crop=True, rand_mirror=True,
                               mean_r=123.0, mean_g=117.0, mean_b=104.0,
                               preprocess_threads=2, shuffle=True)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (8, 3, 32, 32)
    it.reset()
    assert len(list(it)) == 2


def test_image_iter_rank_sharding(tmp_path):
    root = _make_dataset(tmp_path, n=12)
    prefix = str(tmp_path / "shard")
    _im2rec("--list", "--recursive", "--no-shuffle", prefix, root)
    _im2rec("--recursive", prefix, root)
    seen = []
    for part in range(3):
        it = image.ImageIter(batch_size=2, data_shape=(3, 24, 24),
                             path_imgrec=prefix + ".rec", part_index=part,
                             num_parts=3,
                             aug_list=image.CreateAugmenter((3, 24, 24),
                                                            resize=28))
        n = sum(b.data[0].shape[0] - (b.pad or 0) for b in it)
        seen.append(n)
    assert sum(seen) == 12
    assert all(s == 4 for s in seen)


def test_float_resize_preserves_dtype():
    arr = np.random.uniform(-300, 300, (8, 8, 3)).astype(np.float32)
    out = image_backend.resize_image(arr, 4, 4)
    assert out.dtype == np.float32
    # no modulo-256 wrapping: negatives survive and values stay in range
    assert out.min() < 0
    assert arr.min() - 1 <= out.min() and out.max() <= arr.max() + 1


def test_rank_sharding_remainder(tmp_path):
    root = _make_dataset(tmp_path, n=14)
    prefix = str(tmp_path / "rem")
    _im2rec("--list", "--recursive", "--no-shuffle", prefix, root)
    _im2rec("--recursive", prefix, root)
    seen = []
    for part in range(4):
        it = image.ImageIter(batch_size=1, data_shape=(3, 24, 24),
                             path_imgrec=prefix + ".rec", part_index=part,
                             num_parts=4,
                             aug_list=image.CreateAugmenter((3, 24, 24),
                                                            resize=28))
        seen.append(sum(1 for _ in it))
    assert sum(seen) == 14  # remainder samples are not dropped
    assert sorted(seen) == [3, 3, 4, 4]


def test_no_idx_shuffle_and_shard(tmp_path):
    """Without a .idx sidecar, shuffle and sharding must still work (offset
    index built by one sequential scan)."""
    root = _make_dataset(tmp_path, n=12)
    prefix = str(tmp_path / "noidx")
    _im2rec("--list", "--recursive", "--no-shuffle", prefix, root)
    _im2rec("--recursive", prefix, root)
    os.remove(prefix + ".idx")
    seen = []
    for part in range(2):
        it = image.ImageIter(batch_size=2, data_shape=(3, 24, 24),
                             path_imgrec=prefix + ".rec", shuffle=True,
                             part_index=part, num_parts=2,
                             aug_list=image.CreateAugmenter((3, 24, 24),
                                                            resize=28))
        seen.append(sum(b.data[0].shape[0] - (b.pad or 0) for b in it))
    assert seen == [6, 6]


def test_last_batch_discard(tmp_path):
    root = _make_dataset(tmp_path, n=10)
    prefix = str(tmp_path / "disc")
    _im2rec("--list", "--recursive", prefix, root)
    _im2rec("--recursive", prefix, root)
    it = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                         path_imgrec=prefix + ".rec",
                         last_batch_handle="discard",
                         aug_list=image.CreateAugmenter((3, 24, 24),
                                                        resize=28))
    assert len(list(it)) == 2  # 10 // 4, partial batch discarded

"""Real-data end-to-end gate: pack a small ImageNet-style .rec with im2rec,
train ResNet through ImageRecordIter, and measure IO-only throughput via
--test-io (reference: tests/nightly/test_all.sh:43-60 trains from .rec and
gates on accuracy; --test-io per example/image-classification/README:245-268).
"""
import json
import os
import re
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import image_backend

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _make_cls_pack(tmp_path, n=32, size=64, num_classes=2):
    """Class-colored images packed to .rec via the im2rec CLI."""
    rng = np.random.RandomState(0)
    root = tmp_path / "imgs"
    os.makedirs(root, exist_ok=True)
    lines = []
    for i in range(n):
        cls = i % num_classes
        img = (rng.rand(size, size, 3) * 40).astype(np.uint8)
        img[:, :, cls] = np.minimum(img[:, :, cls] + 180, 255)
        fname = "im%03d.png" % i
        with open(root / fname, "wb") as f:
            f.write(image_backend.encode_image(img, ".png"))
        lines.append("%d\t%f\t%s" % (i, float(cls), fname))
    prefix = str(tmp_path / "tinynet")
    with open(prefix + ".lst", "w") as f:
        f.write("\n".join(lines) + "\n")
    subprocess.run([sys.executable, os.path.join(ROOT, "tools", "im2rec.py"),
                    prefix, str(root), "--no-shuffle", "--pass-through"],
                   check=True, capture_output=True)
    return prefix + ".rec"


def _run_driver(extra, timeout=900):
    return subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "examples", "image_classification",
                      "train_imagenet.py")] + extra,
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_resnet_trains_from_rec(tmp_path):
    rec = _make_cls_pack(tmp_path)
    res = _run_driver([
        "--data-train", rec, "--network", "resnet-18", "--num-classes", "2",
        "--image-shape", "3,64,64", "--num-epochs", "5", "--batch-size", "8",
        "--num-examples", "32", "--lr", "0.05", "--lr-step-epochs", "",
        "--disp-batches", "2"])
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-3000:])
    accs = [float(m.group(1)) for m in re.finditer(
        r"Train-accuracy=([0-9.]+)", res.stdout + res.stderr)]
    assert accs, "no Train-accuracy lines in driver output"
    # data shuffling is unseeded in the driver subprocess: gate on the best
    # late-training accuracy, not the single final epoch
    assert max(accs[-3:]) > 0.75, \
        "ResNet did not learn from the .rec: %s" % accs


def test_io_throughput_mode(tmp_path):
    rec = _make_cls_pack(tmp_path)
    res = _run_driver([
        "--data-train", rec, "--test-io", "1", "--num-epochs", "2",
        "--batch-size", "8", "--image-shape", "3,64,64",
        "--num-classes", "2", "--disp-batches", "2"])
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-3000:])
    line = [l for l in res.stdout.splitlines()
            if l.startswith('{"metric": "io_img_per_sec"')][-1]
    rate = json.loads(line)["value"]
    assert rate > 0, line


def test_native_decode_floor_and_thread_scaling():
    """The gate with teeth: the native decode path must sustain the
    per-core floor at full ImageNet resolution (measured 1609 img/s on
    the 1-core dev box — PERF.md input-pipeline section; reference:
    example/image-classification/README.md:245-268), and the GIL-free
    C++ pool must scale on multi-core hosts / never serialize anywhere.
    A libjpeg or batching regression FAILS here, without waiting on any
    training subprocess."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    from bench_decode import run as decode_rate

    r1 = decode_rate(nthreads=1, n_images=128, iters=2)
    assert r1 >= 300, \
        "native 224x224 decode fell below the 300 img/s/core floor: " \
        "%.0f" % r1

    r4 = decode_rate(nthreads=4, n_images=128, iters=2)
    # cores actually usable by THIS process (cgroup quotas shrink it
    # below os.cpu_count() on hosted runners)
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        cores = os.cpu_count() or 1
    if cores >= 4:
        assert r4 >= 1.8 * r1, \
            "decode pool does not scale on %d cores: 1t=%.0f 4t=%.0f" \
            % (cores, r1, r4)
    else:
        # too few cores for threads to help; they must not collapse
        assert r4 >= 0.5 * r1, \
            "decode pool serializes pathologically: 1t=%.0f 4t=%.0f" \
            % (r1, r4)

"""Visualization parity (reference tests/python/unittest/test_viz.py:
print_summary + plot_network over a small symbol)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                             name="conv")
    net = mx.sym.BatchNorm(net, name="bn")
    net = mx.sym.Activation(net, act_type="relu", name="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_print_summary(capsys):
    mx.viz.print_summary(_net(), shape={"data": (1, 3, 16, 16)})
    out = capsys.readouterr().out
    # layer rows, shapes, and a parameter count must all be present
    for token in ("conv", "fc", "Total params"):
        assert token in out, out
    # fc: (512 + 1) * 10 = 5130; bn: 8*2; conv counts only its bias filter
    # term when fed by a bare data variable — the reference print_summary's
    # own accounting quirk, kept for parity
    assert "5130" in out
    assert "Total params: 5154" in out
    assert "8x8x8" in out  # pooled output shape column


def test_plot_network_graphviz():
    graphviz = pytest.importorskip("graphviz")
    dot = mx.viz.plot_network(_net(), shape={"data": (1, 3, 16, 16)},
                              save_format="dot")
    src = dot.source if hasattr(dot, "source") else str(dot)
    assert "conv" in src and "softmax" in src

"""Worker for the kill->relaunch->converge test (run via
``tools/launch.py --auto-resume``).

Attempt 0 trains with per-epoch checkpoints and dies hard (os._exit) after
epoch 2 — a worker crash the launcher must notice. The relaunched attempt
discovers the newest checkpoint with mx.model.find_latest_checkpoint,
resumes from it (the reference's fit.py --load-epoch mechanism,
example/image-classification/common/fit.py:119-128) and trains to
completion, recording final train accuracy and the resumed epoch."""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    workdir = sys.argv[1]
    prefix = os.path.join(workdir, "ar")
    attempt = int(os.environ.get("MXNET_AUTORESUME_ATTEMPT", "0"))
    total_epochs = 10

    rng = np.random.RandomState(0)
    X = rng.randn(120, 10).astype(np.float32)
    w = rng.randn(4, 10).astype(np.float32)
    y = (X @ w.T).argmax(1).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=False)

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=32,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    arg_params = aux_params = None
    begin_epoch = 0
    latest = mx.model.find_latest_checkpoint(prefix)
    if latest is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(prefix, latest)
        begin_epoch = latest

    callbacks = [mx.callback.do_checkpoint(prefix)]
    if attempt == 0:
        # die AFTER epoch 2's checkpoint is on disk, without cleanup
        def crash(epoch, symbol, arg, aux):
            if epoch + 1 >= 2:
                os._exit(17)

        callbacks.append(crash)

    metric = mx.metric.Accuracy()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=total_epochs, begin_epoch=begin_epoch,
            arg_params=arg_params, aux_params=aux_params,
            optimizer="adam", optimizer_params={"learning_rate": 0.05},
            eval_metric=metric, epoch_end_callback=callbacks)

    it.reset()
    metric.reset()
    for batch in it:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    with open(os.path.join(workdir, "result.json"), "w") as f:
        json.dump({"acc": metric.get()[1], "resumed_from": begin_epoch,
                   "attempt": attempt}, f)


if __name__ == "__main__":
    main()

"""Router tests — health/load-aware dispatch, circuit breakers, retries,
hedging, SLO shedding, zero-downtime hot-swap, and the HTTP front door.
CPU-only and fast; the chaos-marked tests drive the failure paths through
a seeded FaultPlan so every failover decision is reproducible."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving


IN_DIM = 6
HID = 3


def _tiny_model(seed=0):
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=HID,
                                name="fc")
    params = {
        "fc_weight": mx.nd.array(rng.randn(HID, IN_DIM).astype(np.float32)),
        "fc_bias": mx.nd.array(rng.randn(HID).astype(np.float32)),
    }
    return net, params


def _reference_outputs(net, params, X):
    pred = mx.Predictor(net, dict(params), {"data": (1, IN_DIM)})
    return np.stack([pred.forward(data=X[i:i + 1])[0].asnumpy()[0]
                     for i in range(len(X))])


def _servers(n=2, net=None, params=None, **kw):
    if net is None:
        net, params = _tiny_model()
    kw.setdefault("max_wait_us", 1000)
    kw.setdefault("warmup", False)
    return net, params, [
        serving.InferenceServer(net, dict(params), {"data": (4, IN_DIM)},
                                **kw) for _ in range(n)]


def test_router_dispatch_matches_reference():
    """Requests fan out over two replicas and every answer matches the
    single-Predictor reference — dispatch is a routing decision, never a
    numerical one."""
    net, params, srvs = _servers(2)
    X = np.random.RandomState(1).randn(10, IN_DIM).astype(np.float32)
    ref = _reference_outputs(net, params, X)
    with serving.Router(srvs, seed=1) as router:
        try:
            futs = [router.submit(data=X[i]) for i in range(10)]
            for i in range(10):
                np.testing.assert_allclose(futs[i].result(timeout=60)[0],
                                           ref[i], rtol=1e-5, atol=1e-6)
            snap = router.metrics.snapshot()
            assert snap["requests"] == {"interactive": 10}
            assert snap["completed"] == {"interactive": 10}
            assert snap["failed"] == {}
            assert router.metrics.latency_quantile(0.5) > 0
            # both replicas took traffic (p2c over equal-score replicas)
            assert sum(d["calls"] for d in router.describe()) == 10
        finally:
            router.close(stop_backends=True)


def test_router_validates_inputs():
    net, params, srvs = _servers(1)
    router = serving.Router(srvs)
    try:
        with pytest.raises(mx.MXNetError):
            router.submit(slo="no-such-class", data=np.zeros(IN_DIM))
        with pytest.raises(ValueError):
            serving.Router([])
    finally:
        router.close(stop_backends=True)
    with pytest.raises(serving.ServerClosedError):
        router.submit(data=np.zeros(IN_DIM, np.float32))
    router.close()  # idempotent


@pytest.mark.chaos
def test_failover_zero_failed_requests_and_breaker_recovery():
    """The acceptance scenario: fault-inject hard failures on one replica
    mid-load.  Every client request still succeeds (bounded retry onto
    the healthy replica), the sick replica's breaker opens after the
    failure threshold, and once the fault clears the breaker walks
    open -> half-open -> closed on a probe request."""
    net, params, srvs = _servers(2)
    X = np.random.RandomState(2).randn(12, IN_DIM).astype(np.float32)
    ref = _reference_outputs(net, params, X)
    router = serving.Router(srvs, seed=3, retries=2, breaker_threshold=3,
                            breaker_cooldown_ms=80)
    try:
        with mx.faults.inject("serving.replica.r1.call:ioerr=1", seed=7):
            for i in range(12):
                out = router.predict(data=X[i])
                np.testing.assert_allclose(out[0], ref[i], rtol=1e-5,
                                           atol=1e-6)
        snap = router.metrics.snapshot()
        assert snap["failed"] == {}          # zero failed client requests
        assert snap["completed"] == {"interactive": 12}
        assert snap["retries"] >= 3          # each r1 failure failed over
        assert snap["replica_failures"]["r1"] >= 3
        assert snap["breaker_transitions"]["open"] >= 1
        states = {d["name"]: d["state"] for d in router.describe()}
        assert states["r1"] == serving.router.BREAKER_OPEN
        assert states["r0"] == serving.router.BREAKER_CLOSED

        # fault cleared: after the cooldown the next pick admits one
        # half-open probe through r1, which succeeds and re-closes it
        time.sleep(0.1)
        for i in range(6):
            router.predict(data=X[i])
        snap = router.metrics.snapshot()
        assert snap["failed"] == {}
        assert snap["breaker_transitions"]["half_open"] >= 1
        assert snap["breaker_transitions"]["closed"] >= 1
        states = {d["name"]: d["state"] for d in router.describe()}
        assert states["r1"] == serving.router.BREAKER_CLOSED
    finally:
        router.close(stop_backends=True)


@pytest.mark.chaos
def test_all_replicas_down_is_a_typed_503():
    net, params, srvs = _servers(1)
    router = serving.Router(srvs, seed=0, retries=2, breaker_threshold=1)
    try:
        with mx.faults.inject("serving.replica.*.call:ioerr=1", seed=1):
            fut = router.submit(data=np.zeros(IN_DIM, np.float32))
            with pytest.raises(serving.NoReplicaAvailableError):
                fut.result(timeout=30)
        assert router.metrics.snapshot()["failed"] == {"interactive": 1}
    finally:
        router.close(stop_backends=True)


@pytest.mark.chaos
def test_hedged_requests_cut_the_tail():
    """With a fixed hedge delay, a call stuck on an injected-slow replica
    is duplicated onto the other one and the fast answer wins — the
    client sees the hedge delay, not the slow replica's latency."""
    net, params, srvs = _servers(2)
    X = np.random.RandomState(4).randn(6, IN_DIM).astype(np.float32)
    ref = _reference_outputs(net, params, X)
    router = serving.Router(srvs, seed=5, hedge_ms=40)
    try:
        with mx.faults.inject("serving.replica.r0.call:delay=1@300ms",
                              seed=2):
            t0 = time.monotonic()
            for i in range(6):
                out = router.predict(data=X[i])
                np.testing.assert_allclose(out[0], ref[i], rtol=1e-5,
                                           atol=1e-6)
            elapsed = time.monotonic() - t0
        snap = router.metrics.snapshot()
        assert snap["failed"] == {}
        assert snap["hedges"] >= 1
        assert snap["hedge_wins"] >= 1
        # 6 un-hedged calls through the slow replica would take >= 1.8s
        assert elapsed < 1.8
    finally:
        router.close(stop_backends=True)


def test_slo_shedding_under_pressure():
    """Admission control sheds the sheddable class (429-with-Retry-After
    semantics) while interactive traffic keeps flowing."""
    net, params, srvs = _servers(1)
    router = serving.Router(srvs, shed_pressure=0.75)
    try:
        router.pressure = lambda: 0.9  # saturate the load signal
        with pytest.raises(serving.RouterOverloadError) as err:
            router.submit(slo="batch", data=np.zeros(IN_DIM, np.float32))
        assert err.value.retry_after > 0
        # interactive is non-sheddable: admitted and served at the same
        # pressure reading
        out = router.predict(data=np.zeros(IN_DIM, np.float32))
        assert out[0].shape == (HID,)
        snap = router.metrics.snapshot()
        assert snap["shed"] == {"batch": 1}
        assert snap["completed"] == {"interactive": 1}
    finally:
        router.close(stop_backends=True)


def test_pressure_reflects_real_backlog():
    net, params, srvs = _servers(1, max_wait_us=200000, max_queue=4)
    router = serving.Router(srvs)
    try:
        assert router.pressure() == 0.0
        futs = [srvs[0].submit(data=np.zeros(IN_DIM, np.float32))
                for _ in range(4)]
        assert router.pressure() == 1.0
        for f in futs:  # flush deadline fires, queue drains
            f.result(timeout=30)
        assert router.pressure() == 0.0
    finally:
        router.close(stop_backends=True)


def test_slo_class_deadline_budget():
    """A class-level deadline budget applies when the request carries
    none: queued past it, the future fails DeadlineExceededError and the
    expiry is accounted per class."""
    net, params, srvs = _servers(1, max_wait_us=300000)
    classes = serving.router.default_slo_classes()
    classes["interactive"] = serving.SLOClass("interactive", deadline_ms=20)
    router = serving.Router(srvs, slo_classes=classes)
    try:
        fut = router.submit(data=np.zeros(IN_DIM, np.float32))
        with pytest.raises(serving.DeadlineExceededError):
            fut.result(timeout=30)
        assert router.metrics.snapshot()["expired"] == {"interactive": 1}
    finally:
        router.close(stop_backends=True)


@pytest.mark.chaos
def test_hot_swap_under_load_zero_downtime(tmp_path):
    """swap() rolls a new checkpoint through the fleet under sustained
    load: no request fails, every answer matches the old or the new
    params, post-swap traffic serves the new ones, and the warm-then-flip
    keeps the recompile counter at zero (steady state never recompiles)."""
    net, params1 = _tiny_model(seed=10)
    _, params2 = _tiny_model(seed=11)
    prefix = str(tmp_path / "swapm")
    mx.model.save_checkpoint(prefix, 1, net,
                             {k: v for k, v in params1.items()}, {})
    mx.model.save_checkpoint(prefix, 2, net,
                             {k: v for k, v in params2.items()}, {})
    X = np.random.RandomState(6).randn(8, IN_DIM).astype(np.float32)
    ref1 = _reference_outputs(net, params1, X)
    ref2 = _reference_outputs(net, params2, X)

    srvs = [serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
        for _ in range(2)]
    router = serving.Router(srvs, seed=7)
    try:
        stop_evt = threading.Event()
        failures = []
        outputs = []

        def load():
            i = 0
            while not stop_evt.is_set():
                try:
                    out = router.predict(data=X[i % len(X)])
                    outputs.append((i % len(X), out[0]))
                except Exception as exc:  # any failure fails the test
                    failures.append(exc)
                i += 1

        threads = [threading.Thread(target=load, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        swapped = router.swap(prefix, 2)
        time.sleep(0.2)
        stop_evt.set()
        for t in threads:
            t.join(timeout=30)

        assert swapped == 2
        assert not failures, failures[:3]
        assert len(outputs) > 0
        for idx, out in outputs:  # old or new params, never garbage
            assert (np.allclose(out, ref1[idx], rtol=1e-5, atol=1e-6)
                    or np.allclose(out, ref2[idx], rtol=1e-5, atol=1e-6))
        # steady state never recompiled: the shadows were warmed on every
        # bucket before their atomic flip into rotation
        assert router.cold_bucket_runs() == 0
        snap = router.metrics.snapshot()
        assert snap["failed"] == {}
        assert snap["swaps"] == 2
        # post-swap traffic runs the new params
        out = router.predict(data=X[0])
        np.testing.assert_allclose(out[0], ref2[0], rtol=1e-5, atol=1e-6)
    finally:
        stop_evt.set()
        router.close(stop_backends=True)


def test_server_inplace_swap(tmp_path):
    """InferenceServer.swap flips the batcher onto warmed shadow
    predictors without restarting: readiness never drops, and requests
    after the flip serve the new params."""
    net, params1 = _tiny_model(seed=12)
    _, params2 = _tiny_model(seed=13)
    prefix = str(tmp_path / "inplace")
    mx.model.save_checkpoint(prefix, 1, net, dict(params1), {})
    mx.model.save_checkpoint(prefix, 2, net, dict(params2), {})
    X = np.random.RandomState(8).randn(4, IN_DIM).astype(np.float32)
    srv = serving.InferenceServer.from_checkpoint(
        prefix, 1, {"data": (4, IN_DIM)}, max_wait_us=1000)
    try:
        ref1 = _reference_outputs(net, params1, X)
        ref2 = _reference_outputs(net, params2, X)
        np.testing.assert_allclose(srv.predict(data=X[0])[0], ref1[0],
                                   rtol=1e-5, atol=1e-6)
        srv.swap(prefix, 2)
        assert srv.ready()  # the swap never took the server out of rotation
        np.testing.assert_allclose(srv.predict(data=X[0])[0], ref2[0],
                                   rtol=1e-5, atol=1e-6)
        assert srv.cold_bucket_runs() == 0
    finally:
        srv.stop()


def test_router_http_front_door():
    net, params, srvs = _servers(2)
    X = np.random.RandomState(9).randn(2, IN_DIM).astype(np.float32)
    ref = _reference_outputs(net, params, X)
    router = serving.Router(srvs, seed=2)
    try:
        host, port = router.serve_http()
        base = "http://%s:%d" % (host, port)
        body = json.dumps({"inputs": {"data": X[0].tolist()}}).encode()
        resp = urllib.request.urlopen(urllib.request.Request(
            base + "/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-SLO-Class": "interactive",
                     "X-Request-Id": "req-http-1"}), timeout=30)
        out = json.loads(resp.read())["outputs"]
        np.testing.assert_allclose(np.asarray(out[0]), ref[0], rtol=1e-5,
                                   atol=1e-6)
        with urllib.request.urlopen(base + "/metrics", timeout=10) as m:
            text = m.read().decode()
        assert "mxtpu_router_requests_total" in text
        assert "mxtpu_router_latency_ms" in text
        with urllib.request.urlopen(base + "/healthz", timeout=10) as h:
            assert h.read() == b"ok"
        with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
            assert r.read() == b"ready"
        with urllib.request.urlopen(base + "/replicas", timeout=10) as r:
            reps = json.loads(r.read())
        assert {d["name"] for d in reps} == {"r0", "r1"}
        assert all(d["state"] == "closed" and d["ready"] for d in reps)
        # a shed class surfaces as 429 + Retry-After, not a generic error
        router.pressure = lambda: 1.0
        try:
            urllib.request.urlopen(urllib.request.Request(
                base + "/predict", data=body,
                headers={"Content-Type": "application/json",
                         "X-SLO-Class": "batch"}), timeout=10)
            raise AssertionError("expected HTTP 429")
        except urllib.error.HTTPError as exc:
            assert exc.code == 429
            assert float(exc.headers["Retry-After"]) > 0
            exc.close()
    finally:
        router.close(stop_backends=True)


def test_remote_replica_backend():
    """A Router can front an InferenceServer it only knows as host:port —
    probes and calls go over HTTP, answers match the reference."""
    net, params, srvs = _servers(1)
    srv = srvs[0]
    X = np.random.RandomState(10).randn(3, IN_DIM).astype(np.float32)
    ref = _reference_outputs(net, params, X)
    host, port = srv.serve_http()
    router = serving.Router(["%s:%d" % (host, port)], seed=4)
    try:
        for i in range(3):
            out = router.predict(data=X[i])
            np.testing.assert_allclose(out[0], ref[i], rtol=1e-5, atol=1e-6)
        d = router.describe()[0]
        assert d["kind"] == "remote" and d["ready"]
        assert router.metrics.snapshot()["completed"] == {"interactive": 3}
    finally:
        router.close()
        srv.stop()


def test_router_dispatch_emits_profiler_frames(tmp_path):
    net, params, srvs = _servers(1)
    trace = str(tmp_path / "router_trace.json")
    router = serving.Router(srvs)
    try:
        mx.profiler.profiler_set_config(mode="all", filename=trace)
        mx.profiler.profiler_set_state("run")
        router.predict(data=np.zeros(IN_DIM, np.float32))
        mx.profiler.profiler_set_state("stop")
        mx.profiler.dump_profile()
    finally:
        router.close(stop_backends=True)
    with open(trace) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert any(n.startswith("router/dispatch") for n in names)
    assert any(n.startswith("router/call") for n in names)


def test_breaker_half_open_admits_exactly_one_concurrent_probe():
    """The check-then-act race: N dispatcher threads all see a half-open
    idle breaker at once — try_reserve must hand the probe slot to
    exactly one of them, and release/end_call must hand it back."""
    _, _, srvs = _servers(1)
    router = serving.Router(srvs)
    try:
        rep = router.replicas()[0]
        rep.state = serving.router.BREAKER_HALF_OPEN
        rep._probe_inflight = False
        wins = []
        barrier = threading.Barrier(8)

        def contender():
            barrier.wait()
            if rep.try_reserve():
                wins.append(1)

        threads = [threading.Thread(target=contender) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1
        assert not rep.try_reserve()        # slot is held
        rep.release()                       # reservation never became a call
        assert rep.try_reserve()            # slot handed back
        rep.end_call(True, 1.0)            # probe success: breaker closes
        assert rep.state == serving.router.BREAKER_CLOSED
        assert rep.try_reserve()            # closed admits everything
    finally:
        router.close(stop_backends=True)


def test_remote_probe_debounce(monkeypatch):
    """One slow /healthz under load must not flap the replica: cached
    health flips down only after K consecutive probe failures, and one
    success flips it straight back up."""
    monkeypatch.setenv("MXNET_SERVING_PROBE_FAILURES", "3")
    _, _, srvs = _servers(1)
    srv = srvs[0]
    host, port = srv.serve_http()
    router = serving.Router(["%s:%d" % (host, port)], seed=5)
    try:
        rep = router.replicas()[0]
        rep._probe()
        assert rep.ready() and rep.alive()
        # sever the backend: probes now fail, but the cache holds
        good_base = rep._base
        rep._base = "http://127.0.0.1:1"    # nothing listens here
        rep._probe()
        assert rep.ready() and rep.alive()  # miss 1: debounced
        rep._probe()
        assert rep.ready() and rep.alive()  # miss 2: debounced
        rep._probe()
        assert not rep.ready() and not rep.alive()  # miss 3 == K: down
        rep._base = good_base
        rep._probe()
        assert rep.ready() and rep.alive()  # one success: up immediately
    finally:
        router.close()
        srv.stop()


def test_remote_probe_first_contact_is_not_debounced():
    """A backend that was never up must not be routed to for K probe
    periods — the first-contact miss counts immediately."""
    _, _, srvs = _servers(1)
    router = serving.Router(srvs)   # anchor replica so dispatch still works
    try:
        dead = serving.router._RemoteReplica(
            "dead", "127.0.0.1:1", router)
        dead._probe()
        assert not dead.ready() and not dead.alive()
    finally:
        router.close(stop_backends=True)


def test_router_dynamic_add_remove_replica():
    """The autoscaler's actuation surface: add_replica puts a backend in
    rotation (traffic reaches it), remove_replica drains it out and
    returns the backend; duplicate names are rejected."""
    net, params, srvs = _servers(1)
    router = serving.Router(srvs, seed=6)
    try:
        extra = serving.InferenceServer(
            net, dict(params), {"data": (4, IN_DIM)},
            max_wait_us=1000, warmup=False)
        name = router.add_replica(extra)
        assert len(router.replicas()) == 2
        with pytest.raises(mx.MXNetError):
            router.add_replica(extra, name=name)
        X = np.random.RandomState(2).randn(12, IN_DIM).astype(np.float32)
        for i in range(12):
            router.predict(data=X[i])
        calls = {d["name"]: d["calls"] for d in router.describe()}
        assert calls[name] > 0          # the new replica took traffic
        back = router.remove_replica(name, drain_timeout_ms=5000)
        assert back is extra
        assert len(router.replicas()) == 1
        assert router.remove_replica("ghost") is None
        router.predict(data=X[0])       # the survivor still serves
        extra.stop()
    finally:
        router.close(stop_backends=True)


def test_router_registry_sync_converges():
    """Replicated front door: two routers attached to one registry
    converge on the same live set — a registered member appears in both,
    a deregistered member drains out of both."""
    net, params, srvs = _servers(2)
    registry = serving.ReplicaRegistry(ttl_ms=60000)
    registry.register("a", srvs[0])
    routers = [serving.Router(registry=registry, registry_sync_ms=30,
                              seed=i) for i in range(2)]
    try:
        assert all(len(r.replicas()) == 1 for r in routers)
        registry.register("b", srvs[1])

        def names(r):
            return {d["name"] for d in r.describe()}

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if all(names(r) == {"a", "b"} for r in routers):
                break
            time.sleep(0.02)
        assert all(names(r) == {"a", "b"} for r in routers)
        for r in routers:
            r.predict(data=np.zeros(IN_DIM, np.float32))
        registry.deregister("b")
        while time.monotonic() < deadline:
            if all(names(r) == {"a"} for r in routers):
                break
            time.sleep(0.02)
        assert all(names(r) == {"a"} for r in routers)
        for r in routers:                  # both front doors still serve
            r.predict(data=np.zeros(IN_DIM, np.float32))
    finally:
        for r in routers:
            r.close()
        for s in srvs:
            s.stop()
        registry.close()

"""mx.register_pallas_op — the public user-kernel escape hatch (MXRtc
parity, /root/reference/src/common/mxrtc.cc:117-135 and mx.rtc).  Kernels
run through Pallas interpret mode on the CPU test mesh, so the real kernel
path is exercised."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _register_scaled_square():
    """y = alpha * x^2 as a real Pallas kernel with a custom vjp."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref, *, alpha):
        x = x_ref[...]
        o_ref[...] = alpha * x * x

    def fn(attrs, x):
        import functools

        alpha = attrs.get("alpha", 1.0)
        return pl.pallas_call(
            functools.partial(kernel, alpha=alpha),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=jax.default_backend() != "tpu",
        )(x)

    def bwd(attrs, res, ct):
        (x,) = res
        return (2.0 * attrs.get("alpha", 1.0) * x * ct,)

    return mx.register_pallas_op(
        "scaled_square", fn, bwd=bwd,
        params={"alpha": mx.Param(float, 1.0)})


_register_scaled_square()


def test_pallas_op_imperative():
    x = mx.nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    out = mx.nd.scaled_square(x, alpha=2.0)
    np.testing.assert_allclose(out.asnumpy(), [2.0, 8.0, 18.0])


def test_pallas_op_symbolic_forward_backward():
    data = mx.sym.Variable("data")
    net = mx.sym.scaled_square(data, alpha=3.0)
    x = np.array([[1.0, -2.0], [0.5, 4.0]], np.float32)
    ex = net.bind(mx.cpu(), {"data": mx.nd.array(x)},
                  args_grad={"data": mx.nd.zeros(x.shape)})
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), 3.0 * x * x,
                               rtol=1e-6)
    ex.backward(out_grads=mx.nd.ones(x.shape))
    # custom bwd: d/dx alpha*x^2 = 2*alpha*x
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), 6.0 * x,
                               rtol=1e-6)


def test_pallas_op_trains_through_module():
    """The registered kernel participates in a fused Module train step."""
    np.random.seed(0)
    X = np.random.randn(64, 8).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.scaled_square(data, alpha=1.0)
    net = mx.sym.FullyConnected(net, num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2})
    args, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in args.values())


def test_flash_attention_registered_via_pallas_op():
    """_contrib_FlashAttention is the first user of the public mechanism:
    grads through the registry op must match the dense oracle."""
    import jax

    np.random.seed(1)
    q = np.random.randn(2, 8, 2, 4).astype(np.float32) * 0.3
    k = np.random.randn(2, 8, 2, 4).astype(np.float32) * 0.3
    v = np.random.randn(2, 8, 2, 4).astype(np.float32) * 0.3
    qs, ks, vs = (mx.sym.Variable(n) for n in ("q", "k", "v"))
    net = mx.sym._contrib_FlashAttention(qs, ks, vs, causal=True,
                                         block_q=8, block_k=8)
    ex = net.bind(mx.cpu(), {"q": mx.nd.array(q), "k": mx.nd.array(k),
                             "v": mx.nd.array(v)},
                  args_grad={n: mx.nd.zeros(q.shape) for n in "qkv"})
    ex.forward(is_train=True)
    ex.backward(out_grads=mx.nd.ones(q.shape))

    from mxnet_tpu.parallel.ring import local_attention

    def ref(q, k, v):
        return local_attention(q, k, v, causal=True,
                               scale=1.0 / np.sqrt(4)).sum()

    go = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for name, g in zip("qkv", go):
        np.testing.assert_allclose(ex.grad_dict[name].asnumpy(),
                                   np.asarray(g), rtol=1e-4, atol=1e-5)

"""mx.rtc (runtime kernel-string compilation), mx.th (torch bridge), and
the VGG/GoogLeNet model builders.

Reference parity: src/common/mxrtc.cc:117-135 + python/mxnet/rtc.py (NVRTC
kernel strings), python/mxnet/torch.py (torch function bridge),
example/image-classification/symbols/{vgg,googlenet}.py."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_rtc_kernel_string_compiles_and_runs():
    x = mx.nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    y = mx.nd.ones((2, 4))
    out = mx.nd.zeros((2, 4))
    krnl = mx.rtc.MXRtc("axpy", [("x", x), ("y", y)], [("out", out)], """
    def kernel(x_ref, y_ref, out_ref):
        out_ref[...] = 2.0 * x_ref[...] + y_ref[...]
    """)
    krnl.push([x, y], [out])
    np.testing.assert_allclose(out.asnumpy(), 2 * x.asnumpy() + 1)
    # push twice: compiled object is cached, results stay right
    krnl.push([y, y], [out])
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 4), 3.0))


def test_rtc_rejects_bad_source():
    x = mx.nd.ones((2,))
    with pytest.raises(mx.base.MXNetError, match="compile"):
        mx.rtc.MXRtc("bad", [("x", x)], [("o", x)], "def kernel(: syntax")


def test_torch_bridge():
    if not mx.th.is_available():
        pytest.skip("torch not available")
    x = mx.nd.array(np.array([[1.0, 4.0], [9.0, 16.0]], np.float32))
    out = mx.th.sqrt(x)
    assert isinstance(out, mx.nd.NDArray)
    np.testing.assert_allclose(out.asnumpy(), [[1, 2], [3, 4]])
    # nested namespace + multi-output
    u, s, v = mx.th.linalg.svd(x)
    assert isinstance(s, mx.nd.NDArray) and s.shape == (2,)
    # apply() with dotted name
    out2 = mx.th.apply("clamp", x, min=2.0, max=10.0)
    np.testing.assert_allclose(out2.asnumpy(), [[2, 4], [9, 10]])


def test_inception_v3_builder():
    net = mx.models.get_inception_v3(num_classes=10)
    # canonical 299x299 input shape resolves through the whole stack
    _, out_shapes, _ = net.infer_shape(data=(4, 3, 299, 299))
    assert out_shapes[0] == (4, 10)
    # small spatial size for a fast CPU forward (global_pool absorbs it)
    ex = net.simple_bind(mx.cpu(), data=(2, 3, 96, 96),
                         softmax_label=(2,), grad_req="null")
    ex.forward(is_train=False)
    out = ex.outputs[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(2), rtol=1e-4)


@pytest.mark.parametrize("builder,kwargs,n_args", [
    ("get_vgg", {"num_layers": 11, "num_classes": 10}, None),
    ("get_googlenet", {"num_classes": 10}, None),
])
def test_new_model_builders_infer_and_run(builder, kwargs, n_args):
    net = getattr(mx.models, builder)(**kwargs)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 3, 64, 64))
    assert out_shapes[0] == (2, 10)
    ex = net.simple_bind(mx.cpu(), data=(2, 3, 64, 64),
                         softmax_label=(2,), grad_req="null")
    ex.forward(is_train=False)
    out = ex.outputs[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(2), rtol=1e-4)

"""Failure-detection tests: heartbeat-backed dead-node reporting and barrier
release when a worker dies (reference: ps::Postoffice::GetDeadNodes surfaced
as kvstore.get_num_dead_node, /root/reference/src/kvstore/kvstore_dist.h:
151-160; without it a dead worker hangs the sync merge forever)."""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore_server as kvs


def test_dead_node_detection_via_heartbeats():
    srv = kvs.start_server(num_workers=2, sync_mode=False)
    host, port = srv.addr
    try:
        alive = kvs.ServerClient(host, port)
        doomed = kvs.ServerClient(host, port)
        alive.start_heartbeat(0, interval=0.1)
        doomed.heartbeat(1)  # beats once, then "dies" (no more heartbeats)
        time.sleep(0.5)
        assert alive.dead_nodes(timeout_s=10.0) == []
        dead = alive.dead_nodes(timeout_s=0.3)
        assert dead == [1], dead
        alive.close()
        doomed.close()
    finally:
        srv.stop()


def test_barrier_released_by_dead_worker(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_BARRIER_TIMEOUT", "30")
    monkeypatch.setenv("MXNET_KVSTORE_DEAD_TIMEOUT", "0.5")
    srv = kvs.start_server(num_workers=2, sync_mode=True)
    host, port = srv.addr
    try:
        survivor = kvs.ServerClient(host, port)
        survivor.start_heartbeat(0, interval=0.1)
        dead = kvs.ServerClient(host, port)
        dead.heartbeat(1)
        dead.close()  # worker 1 dies before reaching the barrier

        t0 = time.time()
        with pytest.raises(mx.base.MXNetError, match="dead workers"):
            survivor.barrier()
        # released by deadness detection, NOT the 30s barrier timeout
        assert time.time() - t0 < 10
        survivor.close()
    finally:
        srv.stop()


def test_barrier_aborts_within_unified_heartbeat_timeout(monkeypatch):
    """A parked barrier must surface the dead-peer error within (roughly)
    MXNET_KVSTORE_HEARTBEAT_TIMEOUT — the ONE knob every liveness
    consumer (dead_nodes RPC, barrier release, DistSync) now reads — not
    after the much longer barrier timeout."""
    monkeypatch.setenv("MXNET_KVSTORE_BARRIER_TIMEOUT", "60")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_TIMEOUT", "0.5")
    srv = kvs.start_server(num_workers=2, sync_mode=True)
    host, port = srv.addr
    try:
        survivor = kvs.ServerClient(host, port)
        survivor.start_heartbeat(0, interval=0.1)
        dead = kvs.ServerClient(host, port)
        dead.heartbeat(1)
        dead.close()
        t0 = time.time()
        with pytest.raises(mx.base.MXNetError, match="dead workers"):
            survivor.barrier()
        # the barrier's liveness poll runs once a second, so the abort
        # lands within timeout + one poll + slack — never the 60s wait
        assert time.time() - t0 < 5
        # the RPC view agrees with the barrier's verdict (same default)
        assert survivor.dead_nodes() == [1]
        survivor.close()
    finally:
        srv.stop()


def test_never_heartbeated_ranks_are_not_dead():
    """Ranks that never heartbeated are simply not tracked: bringing a
    fleet up slowly must not read as mass death (the launcher owns
    workers that never came up at all)."""
    srv = kvs.start_server(num_workers=4, sync_mode=False)
    host, port = srv.addr
    try:
        c = kvs.ServerClient(host, port)
        c.heartbeat(0)
        time.sleep(0.3)
        # rank 0 went stale, ranks 1-3 never beat: only 0 is dead
        assert c.dead_nodes(timeout_s=0.1) == [0]
        c.close()
    finally:
        srv.stop()


def test_dist_async_kvstore_reports_dead_nodes(monkeypatch):
    monkeypatch.delenv("DMLC_PS_ROOT_URI", raising=False)
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.setenv("MXNET_KVSTORE_HEARTBEAT_INTERVAL", "0.1")
    kv = mx.kvstore.create("dist_async")
    try:
        assert kv.get_num_dead_node(timeout=30) == 0
        # a peer that heartbeated once and went silent
        host, port = kv._server.addr
        ghost = kvs.ServerClient(host, port)
        ghost.heartbeat(7)
        ghost.close()
        time.sleep(0.4)
        assert kv.get_num_dead_node(timeout=0.2) == 1
    finally:
        kv.close()


def test_dist_sync_single_process_dead_nodes():
    kv = mx.kvstore.create("dist_sync")
    assert kv.get_num_dead_node() == 0

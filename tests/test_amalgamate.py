"""The single-artifact predict bundle (amalgamation parity — reference
amalgamation/README.md:1-14): build the .pyz, run it in a clean
subprocess against a trained checkpoint, match in-process outputs."""
import json
import os
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pyz_predicts_like_in_process(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import amalgamate

    # tiny trained model -> checkpoint
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=3), name="softmax")
    rng = np.random.RandomState(0)
    X = rng.randn(48, 10).astype("f")
    Y = (X[:, 0] > 0).astype("f") + (X[:, 1] > 0)
    it = mx.io.NDArrayIter(X, Y, batch_size=16)
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=4, optimizer_params={"learning_rate": 0.3})
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 4)

    # in-process prediction
    pred = mx.predictor.Predictor.from_checkpoint(prefix, 4,
                                                  {"data": (8, 10)})
    pred.forward(data=X[:8])
    want = pred.get_output(0)
    want = want.asnumpy() if hasattr(want, "asnumpy") else np.asarray(want)

    # bundle + subprocess prediction
    pyz = amalgamate.build(str(tmp_path / "mxtpu_predict.pyz"))
    assert os.path.getsize(pyz) > 10000
    np.save(str(tmp_path / "x.npy"), X[:8])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)  # the bundle must be self-contained
    proc = subprocess.run(
        [sys.executable, pyz, "--prefix", prefix, "--epoch", "4",
         "--input", str(tmp_path / "x.npy"),
         "--output", str(tmp_path / "out.npy")],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-800:]
    got = np.load(str(tmp_path / "out.npy"))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
    # stdout: topk lines, one per row
    assert len(proc.stdout.strip().splitlines()) == 8

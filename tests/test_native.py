"""Native runtime (src/recordio.cc via ctypes): format interchangeability
with the Python RecordIO implementation and threaded-prefetch ordering."""

import os

import numpy as np
import pytest

from mxnet_tpu import native, recordio

pytestmark = pytest.mark.skipif(not native.have_native(),
                                reason="native library unavailable")


def _payloads(n=50, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.bytes(rng.randint(1, 2000)) for _ in range(n)]


def test_python_write_native_read(tmp_path):
    path = str(tmp_path / "a.rec")
    payloads = _payloads()
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = native.NativeRecordReader(path)
    got = []
    while True:
        s = r.read()
        if s is None:
            break
        got.append(s)
    r.close()
    assert got == payloads


def test_native_write_python_read(tmp_path):
    path = str(tmp_path / "b.rec")
    payloads = _payloads(seed=1)
    w = native.NativeRecordWriter(path)
    offsets = [w.write(p) for p in payloads]
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        s = r.read()
        if s is None:
            break
        got.append(s)
    r.close()
    assert got == payloads
    # offset reads hit the same records
    nr = native.NativeRecordReader(path)
    assert nr.read_at(offsets[10]) == payloads[10]
    assert nr.read_at(offsets[0]) == payloads[0]
    nr.close()


def test_native_prefetch_ordering(tmp_path):
    path = str(tmp_path / "c.rec")
    payloads = _payloads(n=200, seed=2)
    w = native.NativeRecordWriter(path)
    for p in payloads:
        w.write(p)
    w.close()
    pf = native.NativePrefetchReader(path, capacity=4)
    got = list(pf)
    pf.close()
    assert got == payloads


def test_native_reader_reset(tmp_path):
    path = str(tmp_path / "d.rec")
    w = native.NativeRecordWriter(path)
    w.write(b"one")
    w.write(b"two")
    w.close()
    r = native.NativeRecordReader(path)
    assert r.read() == b"one"
    r.reset()
    assert r.read() == b"one"
    assert r.read() == b"two"
    assert r.read() is None
    r.close()


def test_corrupt_stream_raises(tmp_path):
    path = str(tmp_path / "e.rec")
    with open(path, "wb") as f:
        f.write(b"\x00" * 32)
    r = native.NativeRecordReader(path)
    with pytest.raises(IOError):
        r.read()
    r.close()


def test_fallback_env_flag(tmp_path, monkeypatch):
    """MXNET_USE_NATIVE=0 forces the pure-Python path (fresh loader
    state)."""
    monkeypatch.setattr(native, "_TRIED", False)
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setenv("MXNET_USE_NATIVE", "0")
    assert native.get_lib() is None
    monkeypatch.setattr(native, "_TRIED", False)
    monkeypatch.delenv("MXNET_USE_NATIVE")
    assert native.get_lib() is not None


def _magic_payloads():
    import struct

    magic = struct.pack("<I", 0xced7230a)
    return [
        magic,                                   # exactly the magic
        b"abcd" + magic + b"efgh",               # aligned magic inside
        b"ab" + magic + b"cdef",                 # unaligned magic (no split)
        magic + magic + b"tail",                 # consecutive aligned magics
        b"x" * 8 + magic,                        # magic at aligned end
    ]


def test_multipart_python_roundtrip(tmp_path):
    path = str(tmp_path / "multi.rec")
    w = recordio.MXRecordIO(path, "w")
    for p in _magic_payloads():
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in _magic_payloads():
        assert r.read() == p
    assert r.read() is None
    r.close()


def test_multipart_python_write_native_read(tmp_path):
    path = str(tmp_path / "multi_pn.rec")
    w = recordio.MXRecordIO(path, "w")
    for p in _magic_payloads():
        w.write(p)
    w.close()
    r = native.NativeRecordReader(path)
    for p in _magic_payloads():
        assert bytes(r.read()) == p
    assert r.read() is None


def test_multipart_native_write_python_read(tmp_path):
    path = str(tmp_path / "multi_np.rec")
    w = native.NativeRecordWriter(path)
    for p in _magic_payloads():
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in _magic_payloads():
        assert r.read() == p
    assert r.read() is None
    r.close()

"""SSD detection graph tests (BASELINE config #4; reference example/ssd/):
training symbol learns on synthetic box data, detection symbol produces
decoded NMS'd boxes."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import get_ssd_detect, get_ssd_train


def _synthetic_boxes(n, size=64, seed=0):
    """Images with one bright square on dark background; label row
    [cls, xmin, ymin, xmax, ymax] normalized, padded with -1 rows."""
    rng = np.random.RandomState(seed)
    data = np.zeros((n, 3, size, size), np.float32)
    label = np.full((n, 4, 5), -1.0, np.float32)
    for i in range(n):
        s = rng.randint(size // 4, size // 2)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        cls = rng.randint(0, 2)
        chan = 0 if cls == 0 else 2
        data[i, chan, y0:y0 + s, x0:x0 + s] = 1.0
        label[i, 0] = [cls, x0 / size, y0 / size, (x0 + s) / size,
                       (y0 + s) / size]
    return data, label


def test_ssd_train_loss_falls():
    np.random.seed(0)
    data, label = _synthetic_boxes(32)
    net = get_ssd_train(num_classes=2, num_filters=(8, 16, 16, 16))
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())
    it = mx.io.NDArrayIter(data=data, label=label, batch_size=8,
                           label_name="label")
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.2,
                                         "momentum": 0.9})
    losses = []
    for epoch in range(8):
        it.reset()
        tot, nb = 0.0, 0
        for batch in it:
            mod.forward(batch, is_train=True)
            outs = mod.get_outputs()
            # outputs: [cls_prob (b,C,A), loc_loss (b,A4), cls_label (b,A)]
            cls_prob = outs[0].asnumpy()
            cls_target = outs[2].asnumpy()
            valid = cls_target >= 0
            idx = np.maximum(cls_target.astype(int), 0)
            picked = np.take_along_axis(
                cls_prob, idx[:, None, :], axis=1)[:, 0, :]
            ce = -np.log(np.maximum(picked, 1e-8))[valid].mean()
            loc = outs[1].asnumpy().sum() / max(valid.sum(), 1)
            tot += ce + loc
            nb += 1
            mod.backward()
            mod.update()
        losses.append(tot / nb)
    assert losses[-1] < losses[0] * 0.7, \
        "SSD loss did not fall: %s" % losses


def test_ssd_detect_output_format():
    np.random.seed(0)
    det = get_ssd_detect(num_classes=2, num_filters=(8, 16, 16, 16))
    exe = det.simple_bind(mx.cpu(), data=(2, 3, 64, 64), grad_req="null")
    for name, arr in exe.arg_dict.items():
        if name == "data":
            continue
        arr[:] = np.random.uniform(-0.1, 0.1, arr.shape).astype(np.float32)
    exe.arg_dict["data"][:] = np.random.uniform(0, 1, (2, 3, 64, 64))
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape[0] == 2 and out.shape[2] == 6
    # rows are [cls_id, score, xmin, ymin, xmax, ymax]; suppressed rows -1
    scores = out[:, :, 1]
    assert ((scores <= 1.0) | (scores == -1)).all()

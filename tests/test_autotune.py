"""Persistent-autotuner tests — the PR-13 acceptance criteria as
assertions.

Search-space enumeration is deterministic and clamp-stable, the Tuner
picks one reproducible winner (ties break on canonical JSON), the
TuningDB round-trips through a fresh instance, tuned and untuned
executables never share a compile-cache digest, AOT bundles carry the
tuning entries, and — the headline — a fresh process in lookup mode
inherits a record-mode winner with ZERO re-tuning.  Chaos-marked:
corrupt/torn tuning entries degrade to the built-in default config
with a structured telemetry event, never a crash.
"""
import importlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autotune, compile_cache as cc, faults, telemetry
from mxnet_tpu.autotune import spaces
from mxnet_tpu.ops.attention import resolve_blocks

atdb = importlib.import_module("mxnet_tpu.autotune.db")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "autotune_worker.py")
ADMIN = os.path.join(ROOT, "tools", "autotune_admin.py")

IN_DIM = 6
HID = 3


def _reset():
    telemetry._reset_for_tests()
    autotune.reset_for_tests()
    cc.reset_stats()


@pytest.fixture
def tune_dir(tmp_path, monkeypatch):
    """Fresh tuning-DB dir in record mode, clean counters both sides."""
    d = str(tmp_path / "at")
    monkeypatch.setenv("MXNET_AUTOTUNE_DIR", d)
    monkeypatch.setenv("MXNET_AUTOTUNE", "record")
    _reset()
    yield d
    _reset()


def _put(site, key, config, d=None):
    db = autotune.db() if d is None else atdb.TuningDB(d)
    db.put(site, key, config, {"objective": "test"})
    return db


# ---------------------------------------------------------------------------
# search spaces
# ---------------------------------------------------------------------------

def test_flash_space_dedup_and_clamp_stability():
    cands = spaces.flash_blocks(512, 512)
    pairs = [(c["block_q"], c["block_k"]) for c in cands]
    assert len(pairs) == len(set(pairs)), "duplicate effective configs"
    assert (512, 512) in pairs and (128, 512) in pairs
    from mxnet_tpu.ops.attention import _pick_block
    for bq, bk in pairs:  # every candidate is its own clamp fixpoint
        assert _pick_block(bq, 512) == bq and _pick_block(bk, 512) == bk
    # short sequences collapse the grid instead of offering dead configs
    short = [(c["block_q"], c["block_k"]) for c in spaces.flash_blocks(64, 64)]
    assert short == [(64, 64)]


def test_fused_and_engine_spaces():
    with_don = spaces.fused_step(donate_allowed=True)
    assert {"remat": 0, "donate": 1} in with_don and len(with_don) == 4
    no_don = spaces.fused_step(donate_allowed=False)
    assert all(c["donate"] == 0 for c in no_don) and len(no_don) == 2

    eng = spaces.decode_engine(8, 256)
    assert all(c["page_size"] <= 256 for c in eng)
    assert any(c["lane_buckets"] == [1, 2, 4, 8] for c in eng)
    srv = spaces.serving_buckets(8)
    assert any(c["buckets"] == [1, 2, 4, 8] for c in srv)
    assert all(c["buckets"][-1] == 8 for c in srv)


# ---------------------------------------------------------------------------
# tuner + DB
# ---------------------------------------------------------------------------

def test_deterministic_winner_ties_break_canonically(tune_dir):
    cands = [{"x": 3}, {"x": 1}, {"x": 2}]
    winners = []
    for i in range(2):
        w = autotune.Tuner(autotune.db()).tune(
            "t_site", {"run": i}, cands, score_fn=lambda c: 1.0)
        winners.append(w)
    # all scores tie: the canonical-JSON smallest candidate wins, twice
    assert winners == [{"x": 1}, {"x": 1}]
    w = autotune.Tuner(autotune.db()).tune(
        "t_site", {"run": 3}, cands, score_fn=lambda c: c["x"])
    assert w == {"x": 1}


def test_db_roundtrip_fresh_instance(tune_dir):
    key = {"seq": 7, "flavor": "roundtrip"}
    _put("rt_site", key, {"block": 256})
    ent = atdb.TuningDB(tune_dir).get("rt_site", key)  # fresh: disk only
    assert ent is not None and ent["config"] == {"block": 256}
    assert atdb.TuningDB(tune_dir).get("rt_site", {"seq": 8}) is None


def test_off_mode_is_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE", "off")
    monkeypatch.setenv("MXNET_AUTOTUNE_DIR", str(tmp_path / "never"))
    _reset()
    assert autotune.lookup("any", {"k": 1}) is None
    assert autotune.get_or_tune("any", {"k": 1}, candidates=[{"a": 1}],
                                score_fn=lambda c: 0.0,
                                default={"a": 9}) == {"a": 9}
    assert autotune.cache_fingerprint() is None
    assert not os.path.exists(str(tmp_path / "never"))


def test_stale_env_entry_invalidates(tune_dir, monkeypatch):
    key = {"seq": 5}
    _put("env_site", key, {"block": 128})
    # poison the persisted env fingerprint: the entry must miss, loudly
    path = os.path.join(tune_dir, atdb.ls_entries(tune_dir)[0]["digest"]
                        + atdb.ENTRY_SUFFIX)
    meta, payload = atdb._STORE.read_payload(path)
    meta["env"]["jaxlib"] = "0.0.0-other"
    os.remove(path + ".crc32")
    os.remove(path)
    atdb._STORE.write_entry(tune_dir, meta["digest"], meta, payload)
    _reset()
    telemetry.enable(trace=False)
    assert autotune.lookup("env_site", key) is None
    assert "autotune_invalidate" in [e["kind"] for e in telemetry.events()]


# ---------------------------------------------------------------------------
# chaos: corruption and injected faults degrade, never crash
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_corrupt_entry_degrades_to_default(tune_dir):
    key = {"seq": 11}
    _put("chaos_site", key, {"block": 256})
    entry = os.path.join(tune_dir, atdb.ls_entries(tune_dir)[0]["digest"]
                         + atdb.ENTRY_SUFFIX)
    with open(entry, "r+b") as f:  # flip payload bytes: CRC must catch it
        f.seek(-4, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")
    _reset()
    telemetry.enable(trace=False)
    assert autotune.lookup("chaos_site", key) is None
    assert autotune.stats()["errors"] >= 1
    assert "autotune_corrupt" in [e["kind"] for e in telemetry.events()]
    # the tuning loop treats the corrupt entry as a plain miss: get_or_tune
    # re-tunes and REPLACES it
    w = autotune.get_or_tune("chaos_site", key, candidates=[{"block": 512}],
                             score_fn=lambda c: 0.0)
    assert w == {"block": 512}
    _reset()
    assert autotune.lookup("chaos_site", key) == {"block": 512}


@pytest.mark.chaos
def test_injected_load_ioerr_degrades(tune_dir):
    key = {"seq": 13}
    _put("chaos_site", key, {"block": 128})
    _reset()
    with faults.inject("autotune.load:ioerr=1") as plan:
        assert autotune.lookup("chaos_site", key) is None
        assert ("autotune.load", "ioerr", 1) in plan.events
    assert autotune.stats()["errors"] >= 1
    _reset()  # fault cleared: the entry is intact and loads fine
    assert autotune.lookup("chaos_site", key) == {"block": 128}


@pytest.mark.chaos
def test_torn_store_leaves_no_entry(tune_dir):
    with faults.inject("autotune.store:partial=1@0.5"):
        _put("chaos_site", {"seq": 17}, {"block": 64})
    leftovers = [n for n in os.listdir(tune_dir)
                 if n.endswith(atdb.ENTRY_SUFFIX)] \
        if os.path.isdir(tune_dir) else []
    assert not leftovers, "torn store left a partial entry"
    assert autotune.stats()["errors"] >= 1
    _reset()  # memory copy died with the process-equivalent reset
    assert autotune.lookup("chaos_site", {"seq": 17}) is None


@pytest.mark.chaos
def test_strict_mode_raises_on_corrupt(tune_dir, monkeypatch):
    key = {"seq": 19}
    _put("chaos_site", key, {"block": 256})
    entry = os.path.join(tune_dir, atdb.ls_entries(tune_dir)[0]["digest"]
                         + atdb.ENTRY_SUFFIX)
    with open(entry, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")
    _reset()
    monkeypatch.setenv("MXNET_AUTOTUNE_STRICT", "1")
    with pytest.raises(Exception):
        autotune.lookup("chaos_site", key)


# ---------------------------------------------------------------------------
# compile-cache integration
# ---------------------------------------------------------------------------

def _tiny_forward(seed=0):
    rng = np.random.RandomState(seed)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=HID,
                                name="fc")
    params = {
        "fc_weight": mx.nd.array(rng.randn(HID, IN_DIM).astype(np.float32)),
        "fc_bias": mx.nd.array(rng.randn(HID).astype(np.float32)),
    }
    X = rng.randn(2, IN_DIM).astype(np.float32)
    pred = mx.Predictor(net, params, {"data": X.shape})
    return pred.forward(data=X)[0].asnumpy()


def test_tuned_and_untuned_never_share_a_cache_entry(tune_dir, tmp_path,
                                                     monkeypatch):
    """Turning the autotuner on re-keys every executable: the same
    program forwards into a SECOND cache entry, so a tuned fleet can
    never deserialize an untuned executable (or vice versa)."""
    ccdir = str(tmp_path / "cc")
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DIR", ccdir)
    monkeypatch.setenv("MXNET_AUTOTUNE", "off")
    _reset()
    out_untuned = _tiny_forward()
    assert len(cc.ls_entries(ccdir)) == 1
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    _reset()
    out_tuned = _tiny_forward()
    entries = cc.ls_entries(ccdir)
    assert len(entries) == 2, \
        "autotune mode change did not re-key the executable"
    np.testing.assert_array_equal(out_untuned, out_tuned)
    # same mode again: the tuned entry is a plain hit, no third entry
    _reset()
    _tiny_forward()
    assert len(cc.ls_entries(ccdir)) == 2
    assert cc.stats()["hits"] >= 1


def test_aot_bundle_carries_tuning_entries(tune_dir, tmp_path, monkeypatch):
    key = {"seq_q": 512, "seq_k": 512, "head_dim": 128,
           "dtype": "float32", "causal": True}
    _put("flash_attention", key, {"block_q": 128, "block_k": 512})
    bundle = str(tmp_path / "bundle")
    cc.save_bundle(bundle, entries=[])
    assert cc.read_manifest(bundle).get("autotune_entries") == 1
    assert os.path.isdir(os.path.join(bundle, "autotune"))

    # fresh replica: NO tuning dir of its own, lookup mode — the bundle
    # overlay alone must supply the winner
    monkeypatch.setenv("MXNET_AUTOTUNE_DIR", str(tmp_path / "empty"))
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    _reset()
    assert autotune.lookup("flash_attention", key) is None
    _reset()
    cc.attach_bundle(bundle)
    assert autotune.lookup("flash_attention", key) == \
        {"block_q": 128, "block_k": 512}
    assert autotune.stats()["hits"] >= 1


# ---------------------------------------------------------------------------
# tunable sites
# ---------------------------------------------------------------------------

def test_flash_resolve_uses_db_winner(tune_dir, monkeypatch):
    key = {"seq_q": 512, "seq_k": 512, "head_dim": 128,
           "dtype": "float32", "causal": True}
    _put("flash_attention", key, {"block_q": 128, "block_k": 512})
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    _reset()
    assert resolve_blocks(None, None, 512, 512, head_dim=128,
                          dtype=np.dtype("float32"), causal=True) \
        == (128, 512)
    # explicit blocks always win over the DB
    assert resolve_blocks(256, 256, 512, 512, head_dim=128,
                          dtype=np.dtype("float32"), causal=True) \
        == (256, 256)


def test_fused_step_site_records_winner(tune_dir, monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_STEP", "1")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=8)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, IN_DIM))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Xavier(), force_init=True)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1},
                       force_init=True)
    rng = np.random.RandomState(3)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(4, IN_DIM).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 8, (4,)).astype(np.float32))],
        pad=0)
    mod.forward_backward(batch)
    mod.update()
    ex = mod._exec_group.execs[0]
    tuned = getattr(ex, "_fused_autotune", None)
    assert tuned is not None and set(tuned) == {"remat", "donate"}
    s = autotune.stats()
    assert s["stores"] >= 1 and s["tuning_ms"] > 0
    assert atdb.ls_entries(tune_dir), "fused-step winner not persisted"


def test_decode_engine_constructor_consults_db(tune_dir, monkeypatch):
    V, LAYERS, HEADS, HIDDEN, S = 64, 2, 2, 32, 32
    net = mx.models.get_transformer_lm(vocab_size=V, num_layers=LAYERS,
                                       num_heads=HEADS, hidden=HIDDEN,
                                       seq_len=S)
    arg_shapes, _, _ = net.infer_shape(data=(1, S), softmax_label=(1, S))
    rng = np.random.RandomState(0)
    params = {
        name: mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.05)
        for name, shp in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}
    key = {"num_layers": LAYERS, "num_heads": HEADS,
           "head_dim": HIDDEN // HEADS, "max_seq_len": S,
           "max_lanes": 8, "dtype": "float32"}
    _put("decode_engine", key,
         {"page_size": 8, "lane_buckets": [1, 2, 4, 8]})
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    _reset()
    from mxnet_tpu.generation import DecodeEngine
    eng = DecodeEngine(params, vocab_size=V, num_layers=LAYERS,
                       num_heads=HEADS, hidden=HIDDEN, max_seq_len=S,
                       num_pages=48, prefill_len_buckets=(8, 16, 32),
                       warmup=False, start=False)
    try:
        assert eng.page_size == 8
        assert eng.lane_buckets == (1, 2, 4, 8)
        assert autotune.stats()["hits"] >= 1
    finally:
        eng.stop()


def test_serving_bucket_site_consults_db(tune_dir, monkeypatch):
    _put("serving_buckets", {"max_batch": 4}, {"buckets": [2, 4]})
    monkeypatch.setenv("MXNET_AUTOTUNE", "on")
    _reset()
    from mxnet_tpu.serving.server import _autotune_buckets
    assert _autotune_buckets(4) == [2, 4]
    monkeypatch.setenv("MXNET_AUTOTUNE", "off")
    assert _autotune_buckets(4) is None


# ---------------------------------------------------------------------------
# tier-1 guard: the benched shape's block clamping (satellite of record)
# ---------------------------------------------------------------------------

def test_default_blocks_at_benched_shape(monkeypatch):
    """With the autotuner OFF, the s=8192 bench shape must resolve to
    the PERF.md-validated 512/512 (and never bk < bq, which starves the
    MXU contraction)."""
    monkeypatch.delenv("MXNET_AUTOTUNE", raising=False)
    bq, bk = resolve_blocks(None, None, 8192, 8192, head_dim=128,
                            dtype="bfloat16", causal=True)
    assert (bq, bk) == (512, 512)
    assert bk >= bq


# ---------------------------------------------------------------------------
# admin CLI
# ---------------------------------------------------------------------------

def test_admin_ls_verify_prune_show(tune_dir):
    _put("flash_attention", {"seq_q": 512}, {"block_q": 128})

    def run(*args):
        return subprocess.run([sys.executable, ADMIN, *args,
                               "--dir", tune_dir],
                              capture_output=True, text=True, timeout=120)

    ls = run("ls", "--json")
    assert ls.returncode == 0, ls.stderr[-800:]
    entries = json.loads(ls.stdout)
    assert len(entries) == 1 and entries[0]["site"] == "flash_attention"
    ver = run("verify", "--json")
    assert ver.returncode == 0 and json.loads(ver.stdout)["bad"] == 0
    show = run("show-winner", entries[0]["digest"])
    assert show.returncode == 0
    assert json.loads(show.stdout)["config"] == {"block_q": 128}
    assert run("prune", "--max-mb", "64").returncode == 0
    assert atdb.ls_entries(tune_dir)  # under budget: nothing pruned

    # corrupt the entry: verify must flag it and exit non-zero
    path = entries[0]["path"]
    with open(path, "r+b") as f:
        f.seek(-4, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")
    bad = run("verify", "--json")
    assert bad.returncode == 1 and json.loads(bad.stdout)["bad"] == 1


# ---------------------------------------------------------------------------
# the acceptance path: tune once, every later process starts tuned
# ---------------------------------------------------------------------------

def _run_worker(tune_dir, mode):
    env = dict(os.environ, MXNET_AUTOTUNE=mode, MXNET_AUTOTUNE_DIR=tune_dir,
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, WORKER], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_fresh_process_inherits_winner_zero_retuning(tune_dir):
    """Record mode pays the tuning cost and picks a NON-default winner
    by the cost proxy; a fresh lookup-mode process lowers with the tuned
    config off the DB — hits, no misses, zero tuning milliseconds."""
    first = _run_worker(tune_dir, "record")
    assert first["stats"]["stores"] >= 1
    assert first["stats"]["tuning_ms"] > 0
    assert tuple(first["blocks"]) != (512, 512), \
        "tuning picked the default — the acceptance shape must move"

    second = _run_worker(tune_dir, "on")
    assert second["blocks"] == first["blocks"]
    assert second["stats"]["hits"] >= 1
    assert second["stats"]["misses"] == 0
    assert second["stats"]["tuning_ms"] == 0, "lookup mode re-tuned"
    assert second["fingerprint"] == first["fingerprint"]

"""Smoke the BASELINE example CLIs as real subprocesses — the exact
entry points a migrating user runs (reference configs:
train_mnist.py, lstm_bucketing.py, model-parallel lstm; train_imagenet
and train_ssd are exercised by test_real_data_e2e/test_detection_io).
Tiny shapes; asserts exit 0 and a sane final log line, not accuracy
(the convergence gates live in the module/e2e tests)."""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(rel, args, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=ROOT + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, rel)] + args,
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=ROOT)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-1200:]
    return proc.stdout + proc.stderr


def test_train_mnist_cli():
    out = _run("examples/image_classification/train_mnist.py",
               ["--network", "mlp", "--num-epochs", "1",
                "--num-examples", "600", "--batch-size", "50",
                "--lr", "0.2"])
    assert "accuracy" in out.lower()


def test_lstm_bucketing_cli():
    out = _run("examples/rnn/lstm_bucketing.py",
               ["--num-epochs", "1", "--num-hidden", "32",
                "--num-embed", "32", "--batch-size", "16"])
    assert "perplexity" in out.lower() or "ppl" in out.lower()


def test_model_parallel_lstm_cli():
    out = _run("examples/model_parallel_lstm/lstm.py",
               ["--num-epochs", "1", "--num-hidden", "32",
                "--num-embed", "32", "--batch-size", "16"])
    assert "epoch" in out.lower()


def test_train_lm_cli_benchmark():
    out = _run("examples/transformer/train_lm.py",
               ["--benchmark", "1", "--seq-len", "128", "--hidden", "64",
                "--num-layers", "1", "--num-heads", "2",
                "--batch-size", "2", "--num-steps", "2", "--warmup", "1",
                "--vocab-size", "128"])
    assert "tokens_per_sec" in out

"""Sparse parameter plane — row-sparse values, sharded tables,
server-placed optimizers, and the DLRM end-to-end acceptance.

Covers the four layers of mxnet_tpu/sparse/ plus the wire/crash
contracts they inherit from the elastic kvstore:

* RowSparseArray semantics and the O(touched-rows) Embedding gradient
  (bit-exact against the dense autodiff gradient);
* push_rows/pull_rows exactly-once replay under a dropped ACK;
* `row % num_servers` sharding (no server holds a full table) and
  deterministic lazy row init;
* server-placed SGD/AdaGrad parity with a numpy reference, journaled
  into v4 snapshots and restored bit-exact;
* sync-mode sparse merge rounds with elastic shrink renormalization;
* acceptance: a 2-server sharded DLRM where workers stay O(touched),
  one server is SIGKILLed mid-run and the snapshot-restart resumes
  bit-identical to an uninterrupted run, and the sparse path matches a
  dense-embedding reference run bit-exactly on a small table.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu import kvstore_server as kvs
from mxnet_tpu import sparse
from mxnet_tpu.io import DataBatch
from mxnet_tpu.ops.indexing import embedding_row_sparse_grad
from mxnet_tpu.sparse.plane import SparseParamPlane
from mxnet_tpu.sparse.updaters import (SparseAdaGrad, SparseSGD,
                                       from_dense_optimizer)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_fleet(n, **kw):
    """n in-process servers + clients + a plane over them."""
    srvs = [kvs.start_server(port=0, **kw) for _ in range(n)]
    clients = [kvs.ServerClient(*s.addr) for s in srvs]
    return srvs, clients, SparseParamPlane(clients)


def _stop_fleet(clients):
    for c in clients:
        try:
            c.stop_server()
        except Exception:
            pass
        c.close()


# ---------------------------------------------------------------------------
# RowSparse values
# ---------------------------------------------------------------------------

def test_row_sparse_array_roundtrip_and_merge():
    dense = np.zeros((10, 3), np.float32)
    dense[2] = 1.0
    dense[7] = 2.0
    rs = sparse.RowSparseArray.from_dense(dense)
    assert rs.stype == "row_sparse" and rs.nnz == 2
    np.testing.assert_array_equal(rs.indices, [2, 7])
    np.testing.assert_array_equal(rs.to_dense(), dense)
    # duplicate ids sum and canonicalize sorted
    rs2 = sparse.RowSparseArray([7, 2, 7], np.ones((3, 3), np.float32),
                                (10, 3))
    np.testing.assert_array_equal(rs2.indices, [2, 7])
    np.testing.assert_array_equal(rs2.values[1], np.full(3, 2.0))
    ids, vals = sparse.row_merge([5, 1, 5, 1],
                                 np.ones((4, 2), np.float32))
    np.testing.assert_array_equal(ids, [1, 5])
    np.testing.assert_array_equal(vals, np.full((2, 2), 2.0))


def test_embedding_grad_is_o_touched_rows():
    """Tier-1 pin: the row_sparse Embedding gradient allocates O(touched
    rows), never O(vocab) — at vocab=10^6 the dense gradient would be
    32 MB; the sparse one must stay under 2 MB peak."""
    import tracemalloc

    vocab, dim = 1_000_000, 8
    ids = np.array([[5, 999_999, 5, 123_456]], np.int64)
    og = np.random.RandomState(0).randn(1, 4, dim).astype(np.float32)
    tracemalloc.start()
    g = embedding_row_sparse_grad(ids, og, vocab)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert g.shape == (vocab, dim)
    assert g.values.shape[0] == 3          # distinct ids, not vocab
    assert peak < 2 << 20, "grad path allocated %d bytes (O(vocab)?)" % peak
    np.testing.assert_array_equal(g.indices, [5, 123_456, 999_999])
    # the duplicated id's rows summed
    np.testing.assert_array_equal(g.values[0], og[0, 0] + og[0, 2])


def test_embedding_grad_matches_dense_autodiff_bit_exact():
    """Same ids/out_grad through the dense autodiff path (full (vocab,
    dim) cotangent) and the sparse path must agree bit-for-bit."""
    vocab, dim = 10, 4
    # each id appears at most twice: a 2-term sum is order-independent
    # in IEEE float, so bit-exactness is well-defined
    idx = np.array([1.0, 3.0, 1.0, 7.0, 5.0, 3.0], np.float32)
    weight = np.random.RandomState(1).randn(vocab, dim).astype(np.float32)
    data, w = mx.sym.Variable("data"), mx.sym.Variable("weight")
    s = mx.sym.Embedding(data=data, weight=w, input_dim=vocab,
                         output_dim=dim)
    gbuf = mx.nd.zeros((vocab, dim))
    exe = s.bind(mx.cpu(), {"data": mx.nd.array(idx),
                            "weight": mx.nd.array(weight)},
                 args_grad={"weight": gbuf}, grad_req={"weight": "write",
                                                       "data": "null"})
    exe.forward(is_train=True)
    og = np.random.RandomState(2).randn(6, dim).astype(np.float32)
    exe.backward([mx.nd.array(og)])
    dense_g = gbuf.asnumpy()
    sparse_g = embedding_row_sparse_grad(idx, og, vocab)
    assert sparse_g.to_dense().tobytes() == dense_g.tobytes()


# ---------------------------------------------------------------------------
# wire: exactly-once, sharding, lazy init
# ---------------------------------------------------------------------------

def test_push_rows_replay_exactly_once(monkeypatch):
    """A push_rows whose ACK is dropped is replayed under the same
    idempotency token; the server must not apply it twice."""
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "40")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "1")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "20")
    srv = kvs.start_server(num_workers=1)
    try:
        ids = np.array([3, 9], np.int64)
        vals = np.full((2, 4), 5.0, np.float32)
        # client recv #1 is the init_table ACK, #2 the push_rows ACK —
        # dropped after the server has already applied the push
        with faults.inject("kv.client.recv:drop=1@#2") as plan:
            with kvs.ServerClient(*srv.addr) as c:
                c.init_table("t", {"num_rows": 100, "row_shape": (4,),
                                   "init": ("zeros",)})
                c.push_rows("t", ids, vals)
                out = c.pull_rows("t", ids)
            assert plan.events == [("kv.client.recv", "drop", 2)]
        np.testing.assert_array_equal(out, vals)
        assert srv.applied_row_pushes == 1  # replay deduplicated
    finally:
        srv.stop()


def test_two_server_sharding_no_full_table():
    """Row r lives on server r % num_servers — each shard holds its half
    and nothing else; pulls reassemble transparently."""
    srvs, clients, plane = _mk_fleet(2)
    try:
        plane.init_table("emb", num_rows=1000, row_shape=(2,),
                         init=("zeros",))
        ids = np.arange(100, dtype=np.int64)
        vals = np.stack([np.full(2, float(i), np.float32) for i in ids])
        plane.push_rows("emb", ids, vals)
        plane.wait("emb")
        got = plane.pull_rows("emb", ids)
        np.testing.assert_array_equal(got, vals)
        infos = plane.table_info()
        rows = [info["emb"]["rows"] for info in infos]
        assert rows == [50, 50]            # even/odd split, no full table
        assert all(info["emb"]["misplaced"] == 0 for info in infos)
        assert all(r < ids.size for r in rows)
    finally:
        _stop_fleet(clients)


def test_lazy_row_init_deterministic():
    """Untouched rows materialize from an RNG seeded by (key, row) —
    independent of server count, pull order, and restarts."""
    srvs1, clients1, plane1 = _mk_fleet(1)
    srvs2, clients2, plane2 = _mk_fleet(2)
    try:
        for plane in (plane1, plane2):
            plane.init_table("emb", num_rows=50, row_shape=(3,),
                             init=("uniform", 0.1))
        a = plane1.pull_rows("emb", np.array([7, 3, 11], np.int64))
        b = plane2.pull_rows("emb", np.array([3, 7, 11], np.int64))
        assert a[0].tobytes() == b[1].tobytes()   # row 7
        assert a[1].tobytes() == b[0].tobytes()   # row 3
        assert a[2].tobytes() == b[2].tobytes()   # row 11
        # repeat pulls are stable (rows materialized once)
        a2 = plane1.pull_rows("emb", np.array([7, 3, 11], np.int64))
        assert a.tobytes() == a2.tobytes()
        assert a.std() > 0                        # actually random-init
    finally:
        _stop_fleet(clients1)
        _stop_fleet(clients2)


# ---------------------------------------------------------------------------
# server-placed optimizers + snapshots
# ---------------------------------------------------------------------------

def test_server_placed_updaters_match_numpy_reference():
    rng = np.random.RandomState(3)
    g1 = rng.randn(3, 4).astype(np.float32)
    g2 = rng.randn(3, 4).astype(np.float32)
    ids = np.array([1, 5, 9], np.int64)

    # SGD with momentum
    srvs, clients, plane = _mk_fleet(1)
    try:
        plane.init_table("t", num_rows=20, row_shape=(4,), init=("zeros",))
        plane.set_sparse_optimizer(SparseSGD(learning_rate=0.5,
                                             momentum=0.9))
        plane.push_rows("t", ids, g1)
        plane.push_rows("t", ids, g2)
        plane.wait("t")
        got = plane.pull_rows("t", ids)
    finally:
        _stop_fleet(clients)
    w = np.zeros((3, 4), np.float32)
    m = np.zeros((3, 4), np.float32)
    for g in (g1, g2):
        m = (0.9 * m - 0.5 * g).astype(np.float32)
        w = (w + m).astype(np.float32)
    np.testing.assert_allclose(got, w, rtol=1e-6, atol=1e-7)

    # AdaGrad
    srvs, clients, plane = _mk_fleet(1)
    try:
        plane.init_table("t", num_rows=20, row_shape=(4,), init=("zeros",))
        plane.set_sparse_optimizer(SparseAdaGrad(learning_rate=0.5,
                                                 eps=1e-7))
        plane.push_rows("t", ids, g1)
        plane.push_rows("t", ids, g2)
        plane.wait("t")
        got = plane.pull_rows("t", ids)
    finally:
        _stop_fleet(clients)
    w = np.zeros((3, 4), np.float32)
    h = np.zeros((3, 4), np.float32)
    for g in (g1, g2):
        h = (h + g * g).astype(np.float32)
        w = (w - 0.5 * g / (np.sqrt(h) + 1e-7)).astype(np.float32)
    np.testing.assert_allclose(got, w, rtol=1e-6, atol=1e-7)


def test_from_dense_optimizer_maps_hyperparams():
    opt = mx.optimizer.SGD(learning_rate=0.25, wd=0.01, momentum=0.9,
                           rescale_grad=0.125)
    upd = from_dense_optimizer(opt)
    assert isinstance(upd, SparseSGD)
    assert upd.lr == 0.25 and upd.wd == 0.01 and upd.momentum == 0.9
    assert upd.rescale_grad == 0.125


def test_snapshot_v4_roundtrip_restores_tables_bit_exact(tmp_path):
    """kill-safety of the sparse state: tables, per-row optimizer state,
    the installed updater, and the applied-push counter all survive a
    snapshot/restore round trip bit-exactly."""
    snap = str(tmp_path / "kv.snap")
    ids = np.array([2, 3, 8], np.int64)
    g = np.random.RandomState(4).randn(3, 4).astype(np.float32)
    srv1 = kvs.start_server(port=0, snapshot_path=snap, snapshot_interval=0)
    c1 = kvs.ServerClient(*srv1.addr)
    c1.init_table("t", {"num_rows": 100, "row_shape": (4,),
                        "init": ("uniform", 0.05)})
    c1.set_sparse_optimizer(SparseAdaGrad(learning_rate=0.1))
    c1.push_rows("t", ids, g)
    before = c1.pull_rows("t", ids)
    assert c1.snapshot() == snap

    srv2 = kvs.start_server(port=0, snapshot_path=snap, snapshot_interval=0)
    c2 = kvs.ServerClient(*srv2.addr)
    try:
        assert srv2.restored
        assert srv2.applied_row_pushes == srv1.applied_row_pushes == 1
        after = c2.pull_rows("t", ids)
        assert before.tobytes() == after.tobytes()
        # AdaGrad state restored too: the NEXT step matches on both
        c1.push_rows("t", ids, g)
        c2.push_rows("t", ids, g)
        assert (c1.pull_rows("t", ids).tobytes()
                == c2.pull_rows("t", ids).tobytes())
        info = c2.table_info()["t"]
        assert info["rows"] == 3 and info["state_rows"] == 3
    finally:
        for c in (c1, c2):
            try:
                c.stop_server()
            except Exception:
                pass
            c.close()


# ---------------------------------------------------------------------------
# sync-mode sparse merge rounds + elastic shrink
# ---------------------------------------------------------------------------

def test_sync_sparse_merge_rounds_shrink_renormalizes():
    """Sparse pushes accumulate per merge round like dense ones; a
    2-of-3 round after a leave applies num_workers/len(round) times the
    merged rows, and a departed rank's push is discarded."""
    srv = kvs.start_server(num_workers=3, sync_mode=True)
    host, port = srv.addr
    ids = np.array([0, 1, 2], np.int64)
    ones = np.ones((3, 2), np.float32)
    try:
        clients = [kvs.ServerClient(host, port) for _ in range(3)]
        for r, c in enumerate(clients):
            c.join(r)
        clients[0].init_table("t", {"num_rows": 10, "row_shape": (2,),
                                    "init": ("zeros",)})
        for r in (0, 1, 2):
            clients[r].push_rows("t", ids, ones, rank=r)
        np.testing.assert_allclose(clients[0].pull_rows("t", ids),
                                   np.full((3, 2), 3.0))
        clients[2].leave(2)
        for r in (0, 1):
            clients[r].push_rows("t", ids, ones, rank=r)
        # 2 contributions renormalized by 3/2 -> the same +3.0 per round
        np.testing.assert_allclose(clients[0].pull_rows("t", ids),
                                   np.full((3, 2), 6.0))
        # a push from the departed rank is acked but discarded
        clients[2].push_rows("t", ids, np.full((3, 2), 100.0, np.float32),
                             rank=2)
        for r in (0, 1):
            clients[r].push_rows("t", ids, ones, rank=r)
        np.testing.assert_allclose(clients[0].pull_rows("t", ids),
                                   np.full((3, 2), 9.0))
        for c in clients:
            c.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Module integration: sparse vs dense parity (acceptance d)
# ---------------------------------------------------------------------------

def _tiny_embed_net(input_dim, dim=4, bag=4):
    ids = mx.sym.Variable("ids")
    emb = mx.sym.Embedding(data=ids, input_dim=input_dim, output_dim=dim,
                           name="embed")
    pooled = mx.sym.sum(emb, axis=1)
    fc = mx.sym.FullyConnected(data=pooled, num_hidden=1, name="fc")
    lab = mx.sym.Variable("y")
    return mx.sym.LinearRegressionOutput(data=fc, label=lab, name="out")


def test_sparse_module_matches_dense_module_bit_exact(monkeypatch):
    """Acceptance (d): k steps of SparseEmbeddingModule over 2 sharded
    servers land on bit-identical embedding rows AND dense params vs a
    plain Module holding the full (vocab, dim) weight locally."""
    # the dense reference must take the op-by-op update path: the fused
    # train step lets XLA contract scatter-add + SGD into FMA forms whose
    # rounding legitimately differs from any op-granular execution
    monkeypatch.setenv("MXNET_FUSED_STEP", "0")
    vocab, dim, bag, batch, steps = 32, 4, 4, 4, 3
    rng = np.random.RandomState(7)
    # unique ids per batch: summation order of duplicate rows is the one
    # thing the two gradient paths may legally disagree on
    batches = []
    for _ in range(steps):
        ids = rng.choice(vocab, size=batch * bag,
                         replace=False).reshape(batch, bag)
        y = rng.randn(batch, 1).astype(np.float32)
        batches.append((ids.astype(np.float32), y))
    dense_feats = rng.randn(batch, 1).astype(np.float32)  # unused pad

    opt_params = (("learning_rate", 0.05), ("wd", 0.0), ("momentum", 0.0))

    # dense reference: full-vocab weight, local update
    dmod = mx.mod.Module(_tiny_embed_net(vocab, dim, bag),
                         data_names=["ids"], label_names=["y"])
    dmod.bind(data_shapes=[("ids", (batch, bag))],
              label_shapes=[("y", (batch, 1))])
    dmod.init_params(initializer=mx.init.Uniform(0.01))
    dargs, _ = dmod.get_params()
    dargs = {k: v.asnumpy().copy() for k, v in dargs.items()}
    dargs["embed_weight"][:] = 0.0        # match the server zeros init
    dmod.set_params({k: mx.nd.array(v) for k, v in dargs.items()}, {})
    dmod.init_optimizer(kvstore=None, optimizer="sgd",
                        optimizer_params=opt_params)

    # sparse run: capacity-bound weight, 2 sharded servers
    srvs = [kvs.start_server(port=0) for _ in range(2)]
    uris = ",".join("%s:%d" % s.addr for s in srvs)
    monkeypatch.setenv("DMLC_SERVER_URIS", uris)
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    try:
        slots = {"slot": {"data": "ids", "weight": "embed_weight",
                          "num_rows": vocab, "capacity": vocab,
                          "init": ("zeros",)}}
        smod = sparse.SparseEmbeddingModule(
            _tiny_embed_net(vocab, dim, bag), sparse_slots=slots,
            data_names=["ids"], label_names=["y"])
        smod.bind(data_shapes=[("ids", (batch, bag))],
                  label_shapes=[("y", (batch, 1))])
        smod.init_params(arg_params={k: mx.nd.array(v)
                                     for k, v in dargs.items()},
                         aux_params={})
        smod.init_optimizer(kvstore="dist_async", optimizer="sgd",
                            optimizer_params=opt_params)

        for ids, y in batches:
            for m in (dmod, smod):
                m.forward_backward(DataBatch([mx.nd.array(ids)],
                                             [mx.nd.array(y)]))
                m.update()
        smod.sparse_plane.wait()

        table = smod.sparse_plane.pull_rows(
            "embed_weight", np.arange(vocab, dtype=np.int64))
        dense_w = dmod.get_params()[0]["embed_weight"].asnumpy()
        assert table.tobytes() == dense_w.tobytes(), \
            "sparse embedding rows diverge from the dense reference"
        # dense (non-sparse) params took the stock path on both modules
        dfc = dmod.get_params()[0]["fc_weight"].asnumpy()
        sfc = smod.get_params()[0]["fc_weight"].asnumpy()
        assert dfc.tobytes() == sfc.tobytes()
    finally:
        for s in srvs:
            s.stop()


# ---------------------------------------------------------------------------
# acceptance e2e: sharded DLRM, kill -9 a server, resume bit-identical
# ---------------------------------------------------------------------------

def _dlrm_batches(steps, batch, bag, vocab, dense_dim, seed=11):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        dense = rng.randn(batch, dense_dim).astype(np.float32)
        s0 = rng.choice(vocab, size=batch * bag, replace=False)
        s1 = rng.choice(vocab, size=batch * bag, replace=False)
        y = rng.randint(0, 2, size=(batch, 1)).astype(np.float32)
        out.append((dense, s0.reshape(batch, bag).astype(np.float32),
                    s1.reshape(batch, bag).astype(np.float32), y))
    return out


@pytest.mark.chaos
def test_dlrm_two_server_train_kill_restart_bit_identical(
        tmp_path, monkeypatch):
    """The tentpole acceptance: a 2-server sharded DLRM where

    (a) no single server holds the full table,
    (b) worker-resident param bytes stay O(touched rows) while the
        logical table is >= 10x the bound buffer,
    (c) SIGKILL of one server mid-run + snapshot-restart resumes
        bit-identical to an uninterrupted run.
    """
    # the restarted server re-imports the package before it listens:
    # give replayed RPCs a long runway
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX", "120")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_INITIAL_MS", "10")
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_MAX_MS", "500")
    import socket

    from mxnet_tpu.models import get_dlrm

    vocab, dim, cap, bag, batch, dense_dim = 40_000, 16, 128, 4, 8, 8
    steps, kill_after = 6, 3
    batches = _dlrm_batches(steps, batch, bag, vocab, dense_dim)
    probe = _dlrm_batches(1, batch, bag, vocab, dense_dim, seed=99)[0]

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("DMLC_ROLE", None)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(port, snap):
        return subprocess.Popen(
            [sys.executable,
             os.path.join(ROOT, "tests", "chaos_kv_server.py"),
             "127.0.0.1", str(port), snap], env=env, cwd=ROOT)

    def train(tag, interrupt):
        # identical dense-param init across both runs: initializers draw
        # from the framework PRNG stream, not global numpy state
        mx.random.seed(1234)
        np.random.seed(1234)
        ports = [free_port(), free_port()]
        snaps = [str(tmp_path / ("%s-%d.snap" % (tag, i)))
                 for i in range(2)]
        procs = [spawn(p, s) for p, s in zip(ports, snaps)]
        monkeypatch.setenv("DMLC_SERVER_URIS",
                           ",".join("127.0.0.1:%d" % p for p in ports))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        try:
            sym, slots = get_dlrm(
                num_slots=2, vocab_sizes=[vocab, vocab], embed_dim=dim,
                capacity=cap, bag_len=bag, dense_dim=dense_dim,
                bottom_hidden=(16, 8), top_hidden=(16, 8))
            mod = sparse.SparseEmbeddingModule(
                sym, sparse_slots=slots,
                data_names=["dense", "slot0_indices", "slot1_indices"],
                label_names=["ctr_label"])
            mod.bind(data_shapes=[("dense", (batch, dense_dim)),
                                  ("slot0_indices", (batch, bag)),
                                  ("slot1_indices", (batch, bag))],
                     label_shapes=[("ctr_label", (batch, 1))])
            mod.init_params(initializer=mx.init.Uniform(0.01))
            mod.init_optimizer(kvstore="dist_async", optimizer="sgd",
                               optimizer_params=(("learning_rate", 0.05),
                                                 ("wd", 0.0)))
            touched = set()
            for step, (dense, s0, s1, y) in enumerate(batches):
                touched.update(np.unique(s0).astype(int))
                touched.update(np.unique(s1).astype(int))
                mod.forward_backward(DataBatch(
                    [mx.nd.array(dense), mx.nd.array(s0),
                     mx.nd.array(s1)], [mx.nd.array(y)]))
                mod.update()
                if interrupt and step + 1 == kill_after:
                    # quiesce -> snapshot both shards -> SIGKILL one
                    mod.sparse_plane.wait()
                    kv = mod._kvstore
                    if hasattr(kv, "wait_all"):
                        kv.wait_all()
                    for port, snap in zip(ports, snaps):
                        with kvs.ServerClient("127.0.0.1", port) as adm:
                            assert adm.snapshot() == snap
                    procs[1].kill()       # SIGKILL: no farewell snapshot
                    procs[1].wait(timeout=30)
                    procs[1] = spawn(ports[1], snaps[1])
            mod.sparse_plane.wait()

            # (a) sharding: neither shard holds the full table
            infos = mod.sparse_plane.table_info()
            for key in ("slot0_embed_weight", "slot1_embed_weight"):
                rows = [i[key]["rows"] for i in infos]
                total = sum(rows)
                assert all(0 < r < total for r in rows), (key, rows)
                assert all(i[key]["misplaced"] == 0 for i in infos)

            # (b) worker memory: bound buffers, not the table
            stats = mod.sparse_stats()
            for s in stats["slots"].values():
                assert s["logical_bytes"] >= 10 * s["resident_bytes"]

            ids = np.array(sorted(touched), np.int64)
            state = [mod.sparse_plane.pull_rows(k, ids).tobytes()
                     for k in ("slot0_embed_weight", "slot1_embed_weight")]
            mod.forward(DataBatch(
                [mx.nd.array(probe[0]), mx.nd.array(probe[1]),
                 mx.nd.array(probe[2])], [mx.nd.array(probe[3])]),
                is_train=False)
            out = mod.get_outputs()[0].asnumpy().tobytes()
            return state, out
        finally:
            for port in ports:
                try:
                    with kvs.ServerClient("127.0.0.1", port) as adm:
                        adm.stop_server()
                except Exception:
                    pass
            for p in procs:
                if p.poll() is None:
                    p.kill()

    clean_state, clean_out = train("clean", interrupt=False)
    kill_state, kill_out = train("kill", interrupt=True)
    # (c) bit-identical resume
    assert kill_state == clean_state, \
        "sharded tables diverge after kill -9 + snapshot restart"
    assert kill_out == clean_out, \
        "model outputs diverge after kill -9 + snapshot restart"

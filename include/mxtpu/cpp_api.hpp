/* Header-only C++ frontend over the flat C ABI — the cpp-package
 * equivalent (reference cpp-package/include/mxnet-cpp/*.hpp: NDArray,
 * Symbol, Operator, Executor RAII wrappers over c_api.h; the reference
 * generates per-op wrappers with OpWrapperGenerator.py, here the
 * Operator class reaches every registered op by name, which is also how
 * the reference's generated wrappers work underneath).
 *
 * Link against libmxtpu_capi.so; see tests/test_c_api.py's
 * test_cpp_frontend for the compile line and examples/cpp/train.cpp
 * for a full train-a-step demo. */
#ifndef MXTPU_CPP_API_HPP_
#define MXTPU_CPP_API_HPP_

#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu/c_api.h"

namespace mxtpu {

using mx_uint = uint32_t;

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXTPUGetLastError());
}

inline std::string Version() {
  const char* v = nullptr;
  Check(MXTPUGetVersion(&v));
  return v;
}

inline void RandomSeed(int seed) { Check(MXTPURandomSeed(seed)); }

struct Context {
  int dev_type, dev_id;
  static Context Cpu(int id = 0) { return {1, id}; }
  /* accelerator device (TPU in production; reference dev_type 2) */
  static Context Tpu(int id = 0) { return {2, id}; }
};

class NDArray {
 public:
  NDArray() = default;
  NDArray(const std::vector<mx_uint>& shape, Context ctx = Context::Cpu(),
          int dtype_flag = 0) {
    NDArrayHandle h = nullptr;
    Check(MXTPUNDArrayCreate(shape.data(),
                             static_cast<mx_uint>(shape.size()),
                             ctx.dev_type, ctx.dev_id, dtype_flag, &h));
    reset(h);
  }
  static NDArray FromData(const std::vector<float>& data,
                          const std::vector<mx_uint>& shape,
                          Context ctx = Context::Cpu()) {
    NDArray a(shape, ctx);
    Check(MXTPUNDArraySyncCopyFromCPU(a.handle(), data.data(),
                                      data.size() * sizeof(float)));
    return a;
  }

  std::vector<mx_uint> Shape() const {
    mx_uint ndim = 0;
    const mx_uint* dims = nullptr;
    Check(MXTPUNDArrayGetShape(handle(), &ndim, &dims));
    return std::vector<mx_uint>(dims, dims + ndim);
  }
  size_t Size() const {
    size_t n = 1;
    for (auto d : Shape()) n *= d;
    return n;
  }
  std::vector<float> ToVector() const {
    std::vector<float> out(Size());
    Check(MXTPUNDArraySyncCopyToCPU(handle(), out.data(),
                                    out.size() * sizeof(float)));
    return out;
  }
  NDArray Slice(mx_uint begin, mx_uint end) const {
    NDArrayHandle h = nullptr;
    Check(MXTPUNDArraySlice(handle(), begin, end, &h));
    NDArray a;
    a.reset(h);
    return a;
  }
  NDArray Reshape(const std::vector<int>& dims) const {
    NDArrayHandle h = nullptr;
    Check(MXTPUNDArrayReshape(handle(), static_cast<int>(dims.size()),
                              dims.data(), &h));
    NDArray a;
    a.reset(h);
    return a;
  }
  void CopyTo(const NDArray& dst) const {
    Check(MXTPUNDArrayCopyFromTo(handle(), dst.handle()));
  }

  NDArrayHandle handle() const { return h_.get(); }
  void reset(NDArrayHandle h) {
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXTPUNDArrayFree(p);
    });
  }

 private:
  std::shared_ptr<void> h_;
};

class Symbol {
 public:
  Symbol() = default;
  static Symbol Variable(const std::string& name) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string& json) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }
  static Symbol FromFile(const std::string& fname) {
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateFromFile(fname.c_str(), &h));
    return Symbol(h);
  }

  std::string ToJSON() const {
    const char* js = nullptr;
    Check(MXTPUSymbolSaveToJSON(handle(), &js));
    return js;
  }
  std::vector<std::string> ListArguments() const {
    return names_of(&MXTPUSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return names_of(&MXTPUSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return names_of(&MXTPUSymbolListAuxiliaryStates);
  }
  /* Wire named inputs (works on atomic AND loaded symbols — the C
   * Compose contract). */
  void Compose(const std::string& name,
               const std::map<std::string, Symbol>& kwargs) {
    std::vector<const char*> keys;
    std::vector<SymbolHandle> args;
    for (auto& kv : kwargs) {
      keys.push_back(kv.first.c_str());
      args.push_back(kv.second.handle());
    }
    Check(MXTPUSymbolCompose(handle(), name.c_str(),
                             static_cast<mx_uint>(args.size()),
                             keys.data(), args.data()));
  }

  SymbolHandle handle() const { return h_.get(); }
  explicit Symbol(SymbolHandle h) {
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXTPUSymbolFree(p);
    });
  }

 private:
  template <typename F>
  std::vector<std::string> names_of(F fn) const {
    mx_uint n = 0;
    const char** arr = nullptr;
    Check(fn(handle(), &n, &arr));
    std::vector<std::string> out;
    for (mx_uint i = 0; i < n; ++i) out.emplace_back(arr[i]);
    return out;
  }
  std::shared_ptr<void> h_;
};

/* Reference cpp-package Operator (operator.hpp): name an op, set string
 * params, then either CreateSymbol (graph mode) or Invoke (imperative). */
class Operator {
 public:
  explicit Operator(const std::string& op_name) : op_(op_name) {}

  template <typename T>
  Operator& SetParam(const std::string& key, const T& value) {
    std::ostringstream os;
    os << value;
    params_[key] = os.str();
    return *this;
  }
  Operator& SetInput(const std::string& name, const Symbol& sym) {
    for (auto& kv : sym_inputs_) {
      if (kv.first == name)
        throw std::runtime_error("duplicate input name '" + name +
                                 "' for op " + op_);
    }
    sym_inputs_.emplace_back(name, sym);
    return *this;
  }
  /* Named imperative input: Invoke() orders operands by the op's
   * DECLARED input order (MXTPUListOpInputs), so call order does not
   * matter and unknown names fail loudly. */
  Operator& SetInput(const std::string& name, const NDArray& nd) {
    for (auto& kv : nd_inputs_) {
      if (kv.first == name)
        throw std::runtime_error("duplicate input name '" + name +
                                 "' for op " + op_);
    }
    nd_inputs_.emplace_back(name, nd);
    return *this;
  }
  /* Positional imperative input (appended in call order). */
  Operator& AddInput(const NDArray& nd) {
    nd_inputs_.emplace_back("", nd);
    return *this;
  }

  Symbol CreateSymbol(const std::string& name = "") {
    std::vector<const char*> keys, vals;
    for (auto& kv : params_) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    SymbolHandle h = nullptr;
    Check(MXTPUSymbolCreateAtomicSymbol(
        op_.c_str(), static_cast<mx_uint>(keys.size()), keys.data(),
        vals.data(), &h));
    Symbol sym(h);
    std::map<std::string, Symbol> kwargs;
    for (auto& kv : sym_inputs_) kwargs.emplace(kv.first, kv.second);
    sym.Compose(name, kwargs);
    return sym;
  }

  std::vector<NDArray> Invoke() {
    std::vector<NDArrayHandle> ins;
    bool named = !nd_inputs_.empty() && !nd_inputs_.front().first.empty();
    if (named) {
      mx_uint n = 0;
      const char** order = nullptr;
      Check(MXTPUListOpInputs(op_.c_str(), &n, &order));
      std::vector<std::string> want(order, order + n);
      for (auto& name : want) {
        for (auto& kv : nd_inputs_) {
          if (kv.first == name) ins.push_back(kv.second.handle());
        }
      }
      if (ins.size() != nd_inputs_.size()) {
        std::string msg = "op " + op_ + " inputs are [";
        for (auto& w : want) msg += w + " ";
        throw std::runtime_error(msg + "]; got unknown/missing names");
      }
    } else {
      for (auto& a : nd_inputs_) ins.push_back(a.second.handle());
    }
    std::vector<const char*> keys, vals;
    for (auto& kv : params_) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    int n_out = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXTPUImperativeInvoke(op_.c_str(),
                                static_cast<int>(ins.size()), ins.data(),
                                &n_out, &outs,
                                static_cast<int>(keys.size()),
                                keys.data(), vals.data()));
    std::vector<NDArray> result(n_out);
    for (int i = 0; i < n_out; ++i) result[i].reset(outs[i]);
    MXTPUFreeHandleArray(outs);
    return result;
  }

 private:
  std::string op_;
  std::map<std::string, std::string> params_;
  std::vector<std::pair<std::string, Symbol>> sym_inputs_;
  std::vector<std::pair<std::string, NDArray>> nd_inputs_;
};

class Executor {
 public:
  /* grad_reqs: 0 null, 1 write, 3 add (reference OpReqType). */
  Executor(const Symbol& sym, Context ctx,
           const std::vector<NDArray>& args,
           const std::vector<NDArray>& grads = {},
           const std::vector<mx_uint>& grad_reqs = {},
           const std::vector<NDArray>& aux = {}) {
    std::vector<NDArrayHandle> ah, gh, xh;
    for (auto& a : args) ah.push_back(a.handle());
    for (auto& g : grads) gh.push_back(g.handle());
    for (auto& x : aux) xh.push_back(x.handle());
    ExecutorHandle h = nullptr;
    Check(MXTPUExecutorBind(sym.handle(), ctx.dev_type, ctx.dev_id,
                            static_cast<mx_uint>(ah.size()), ah.data(),
                            gh.empty() ? nullptr : gh.data(),
                            grad_reqs.empty() ? nullptr : grad_reqs.data(),
                            static_cast<mx_uint>(xh.size()),
                            xh.empty() ? nullptr : xh.data(), &h));
    h_ = std::shared_ptr<void>(h, [](void* p) {
      if (p) MXTPUExecutorFree(p);
    });
  }

  void Forward(bool is_train) {
    Check(MXTPUExecutorForward(h_.get(), is_train ? 1 : 0));
  }
  void Backward(const std::vector<NDArray>& head_grads = {}) {
    std::vector<NDArrayHandle> hh;
    for (auto& g : head_grads) hh.push_back(g.handle());
    Check(MXTPUExecutorBackward(h_.get(),
                                static_cast<mx_uint>(hh.size()),
                                hh.empty() ? nullptr : hh.data()));
  }
  std::vector<NDArray> Outputs() const {
    mx_uint n = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXTPUExecutorOutputs(h_.get(), &n, &outs));
    std::vector<NDArray> result(n);
    for (mx_uint i = 0; i < n; ++i) result[i].reset(outs[i]);
    MXTPUFreeHandleArray(outs);
    return result;
  }

 private:
  std::shared_ptr<void> h_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_API_HPP_

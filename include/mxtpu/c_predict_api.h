/*
 * C prediction ABI for mxnet_tpu — the language-binding boundary.
 *
 * Signature-compatible with the reference predict API
 * (/root/reference/include/mxnet/c_predict_api.h, implemented at
 * src/c_api/c_predict_api.cc:41-280): load symbol JSON + a .params blob,
 * bind static shapes, then SetInput / Forward / GetOutput.  Backed by the
 * embedded Python runtime (mxnet_tpu.capi_shim) — the C layer is pure
 * marshalling, the compute path is the same jitted executor every other
 * frontend uses.
 *
 * All functions return 0 on success, -1 on failure (message via
 * MXTPUGetLastError).
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;
typedef uint32_t mx_uint;

/* Last error message for this thread (empty string if none). */
const char* MXTPUGetLastError(void);

/*
 * Create a predictor.
 *  symbol_json        : symbol graph JSON (contents of *-symbol.json)
 *  param_bytes/size   : image of a .params file (may be NULL/0 if the
 *                       graph has no parameters)
 *  dev_type           : 1 = cpu, 2 = gpu/accelerator (maps to context)
 *  num_input_nodes    : number of input names
 *  input_keys         : input names
 *  input_shape_indptr : CSR-style offsets into input_shape_data,
 *                       length num_input_nodes + 1
 *  input_shape_data   : concatenated input shapes
 */
int MXTPUPredCreate(const char* symbol_json, const void* param_bytes,
                    int param_size, int dev_type, int dev_id,
                    mx_uint num_input_nodes, const char** input_keys,
                    const mx_uint* input_shape_indptr,
                    const mx_uint* input_shape_data, PredictorHandle* out);

/* Copy float32 data into the named input. size = number of floats. */
int MXTPUPredSetInput(PredictorHandle handle, const char* key,
                      const float* data, mx_uint size);

/* Run the bound forward graph. */
int MXTPUPredForward(PredictorHandle handle);

/* Shape of output `index`; *shape_data stays owned by the library and is
 * valid until the next call on this handle. */
int MXTPUPredGetOutputShape(PredictorHandle handle, mx_uint index,
                            mx_uint** shape_data, mx_uint* shape_ndim);

/* Copy output `index` into data (float32). size = number of floats. */
int MXTPUPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                       mx_uint size);

/* Re-bind to new input shapes sharing weights (MXPredReshape). */
int MXTPUPredReshape(mx_uint num_input_nodes, const char** input_keys,
                     const mx_uint* input_shape_indptr,
                     const mx_uint* input_shape_data, PredictorHandle handle,
                     PredictorHandle* out);

/* Release the predictor. */
int MXTPUPredFree(PredictorHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */

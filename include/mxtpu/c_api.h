/*
 * Core C ABI — NDArray CRUD, serialization, op registry, imperative
 * invoke.  The load-bearing subset of the reference's flat C API
 * (/root/reference/include/mxnet/c_api.h: MXNDArrayCreateEx :114,
 * MXNDArraySyncCopy{From,To}CPU, MXNDArraySave/Load :211+,
 * MXListAllOpNames, MXImperativeInvoke c_api_ndarray.cc:323) — the
 * boundary that made the reference's non-Python frontends possible.
 *
 * Conventions: every function returns 0 on success, -1 on failure with
 * the message readable via MXTPUGetLastError() (thread-local).  Handles
 * are opaque.  Returned SCALAR/STRING STORAGE (shape buffers, name
 * tables) is owned by the library and valid only until the next call on
 * the same thread — copy what you need.  Returned HANDLE ARRAYS from
 * MXTPUNDArrayLoad / MXTPUImperativeInvoke are freshly allocated per
 * call: the caller releases the array with MXTPUFreeHandleArray and each
 * individual NDArrayHandle with MXTPUNDArrayFree (unreleased handles
 * keep their backing arrays alive for the process lifetime).
 *
 * dtype flags are the reference's mshadow enum: 0=float32 1=float64
 * 2=float16 3=uint8 4=int32 5=int8 6=int64.
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef uint32_t mx_uint;

const char* MXTPUGetLastError(void);

/* Declared input order for one op (reference analogue:
 * MXSymbolGetAtomicSymbolInfo's arg descriptions). Name table is
 * thread-local storage, valid until the next call. */
int MXTPUListOpInputs(const char* op_name, mx_uint* out_size,
                      const char*** out_array);

/* Library version string (mx.__version__); thread-local storage. */
int MXTPUGetVersion(const char** out);
/* Seed the global RNG resource (reference MXRandomSeed). */
int MXTPURandomSeed(int seed);

/* Create a zero-filled array. dev_type: 1=cpu, 2=gpu/accelerator. */
int MXTPUNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                       int dev_id, int dtype_flag, NDArrayHandle* out);

int MXTPUNDArrayFree(NDArrayHandle handle);

/* *out_data stays owned by the library (valid until the next call on
 * this thread). */
int MXTPUNDArrayGetShape(NDArrayHandle handle, mx_uint* out_ndim,
                         const mx_uint** out_data);

int MXTPUNDArrayGetDType(NDArrayHandle handle, int* out_dtype);

/* Views/copies (reference MXNDArraySlice / MXNDArrayReshape /
 * MXNDArrayGetContext and imperative CopyFromTo). Slice/Reshape return
 * NEW handles the caller frees. */
int MXTPUNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                      NDArrayHandle* out);
int MXTPUNDArrayReshape(NDArrayHandle handle, int ndim, const int* dims,
                        NDArrayHandle* out);
int MXTPUNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                           int* out_dev_id);
int MXTPUNDArrayCopyFromTo(NDArrayHandle src, NDArrayHandle dst);

/* Synchronous host<->device copies; nbytes must equal the array's byte
 * size in its own dtype. */
int MXTPUNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                                size_t nbytes);
int MXTPUNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                              size_t nbytes);

/* Block until all pending async work completes (engine WaitForAll). */
int MXTPUNDArrayWaitAll(void);

/* Save arrays to a reference-format .params file.  keys may be NULL for
 * a nameless list container. */
int MXTPUNDArraySave(const char* fname, mx_uint num_args,
                     NDArrayHandle* args, const char** keys);

/* Load a .params file.  *out_names has *out_name_size entries (0 for a
 * list container).  *out_arr is a freshly allocated array; the caller
 * owns both the array (release with MXTPUFreeHandleArray) and each
 * handle in it (release with MXTPUNDArrayFree).  *out_names, however,
 * is thread-local string storage valid only until the next call on this
 * thread — copy the names out before making further calls. */
int MXTPUNDArrayLoad(const char* fname, mx_uint* out_size,
                     NDArrayHandle** out_arr, mx_uint* out_name_size,
                     const char*** out_names);

/* Release a handle array returned by MXTPUNDArrayLoad /
 * MXTPUImperativeInvoke (the handles themselves are freed separately
 * via MXTPUNDArrayFree). */
int MXTPUFreeHandleArray(NDArrayHandle* arr);

/* All registered operator names. */
int MXTPUListAllOpNames(mx_uint* out_size, const char*** out_array);

/* Invoke a registered op imperatively.  Attr values are strings, parsed
 * by the op's declarative parameter specs (the attr_parser contract).
 * *outputs is a freshly allocated array; caller releases it with
 * MXTPUFreeHandleArray and each handle with MXTPUNDArrayFree. */
int MXTPUImperativeInvoke(const char* op_name, int num_inputs,
                          NDArrayHandle* inputs, int* num_outputs,
                          NDArrayHandle** outputs, int num_params,
                          const char** param_keys, const char** param_vals);

/* ------------------------------------------------------------------ */
/* KVStore surface — parameter synchronization from C.  Reference
 * analogue: c_api.cc:544-700 (MXKVStoreCreate/Init/Push/Pull/GetType/
 * GetRank/GetGroupSize/Barrier).  The C updater callback
 * (MXKVStoreSetUpdater) is intentionally absent: the updater here is
 * the server-side optimizer (dist_async) or the compiled-in psum
 * (dist_sync); the local store's default merge is summing. */

typedef void* KVStoreHandle;

/* type: "local", "device", "dist_sync", "dist_device_sync",
 * "dist_async" — dist flavors read the DMLC_* env contract. */
int MXTPUKVStoreCreate(const char* type, KVStoreHandle* out);
int MXTPUKVStoreFree(KVStoreHandle handle);
int MXTPUKVStoreInit(KVStoreHandle handle, mx_uint num, const int* keys,
                     NDArrayHandle* vals);
int MXTPUKVStorePush(KVStoreHandle handle, mx_uint num, const int* keys,
                     NDArrayHandle* vals);
/* Fills the caller's NDArray handles in place. */
int MXTPUKVStorePull(KVStoreHandle handle, mx_uint num, const int* keys,
                     NDArrayHandle* vals);
/* *out_type is thread-local storage, valid until the next call. */
int MXTPUKVStoreGetType(KVStoreHandle handle, const char** out_type);
int MXTPUKVStoreGetRank(KVStoreHandle handle, int* out);
int MXTPUKVStoreGetGroupSize(KVStoreHandle handle, int* out);
int MXTPUKVStoreBarrier(KVStoreHandle handle);

/* ------------------------------------------------------------------ */
/* DataIter surface — drive the file-backed input pipeline from C.
 * Reference analogue: c_api.cc:446-543 (MXListDataIters,
 * MXDataIterCreateIter/Next/GetData/GetLabel/GetPadNum/BeforeFirst).
 * Attr values are strings parsed like Python literals: batch_size="8",
 * data_shape="(3, 64, 64)", path_imgrec="train.rec". */

typedef void* DataIterHandle;

/* Creatable iterator names (thread-local storage). */
int MXTPUListDataIters(mx_uint* out_size, const char*** out_array);
int MXTPUDataIterCreate(const char* name, mx_uint num_params,
                        const char** keys, const char** vals,
                        DataIterHandle* out);
/* *out = 1 while a batch is available, 0 at end of epoch. */
int MXTPUDataIterNext(DataIterHandle handle, int* out);
int MXTPUDataIterBeforeFirst(DataIterHandle handle);
/* Current batch tensors; each returned handle is caller-owned
 * (MXTPUNDArrayFree). */
int MXTPUDataIterGetData(DataIterHandle handle, NDArrayHandle* out);
int MXTPUDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out);
/* Zero-padded tail rows in the current batch. */
int MXTPUDataIterGetPadNum(DataIterHandle handle, int* out);
int MXTPUDataIterFree(DataIterHandle handle);

/* ------------------------------------------------------------------ */
/* Symbol surface — build/inspect graphs from C with no Python setup.
 * Reference analogue: c_api_symbolic.cc:54-545 (MXSymbolCreateFromJSON,
 * MXSymbolListArguments/Outputs/AuxiliaryStates, MXSymbolInferShape). */

typedef void* SymbolHandle;
typedef void* ExecutorHandle;

int MXTPUSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXTPUSymbolCreateFromFile(const char* fname, SymbolHandle* out);
/* C-side graph building (reference c_api_symbolic.cc
 * MXSymbolCreateVariable / MXSymbolCreateAtomicSymbol / MXSymbolCompose):
 * create an uncomposed op with string attrs, then wire its inputs in
 * place.  keys==NULL composes positionally. */
int MXTPUSymbolCreateVariable(const char* name, SymbolHandle* out);
int MXTPUSymbolCreateAtomicSymbol(const char* op_name, mx_uint num_param,
                                  const char** keys, const char** vals,
                                  SymbolHandle* out);
int MXTPUSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                       const char** keys, SymbolHandle* args);
/* *out_json is thread-local storage, valid until the next call. */
int MXTPUSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
/* Name tables are thread-local storage, valid until the next call. */
int MXTPUSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                             const char*** out_array);
int MXTPUSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                           const char*** out_array);
int MXTPUSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                   const char*** out_array);
int MXTPUSymbolFree(SymbolHandle sym);

/* Infer all shapes from named input shapes in CSR form (the reference
 * MXSymbolInferShape signature, c_api_symbolic.cc:408): keys[i] names an
 * input whose shape is arg_shape_data[arg_ind_ptr[i] .. arg_ind_ptr[i+1]].
 * On return the three (size, ndim, data) triples describe argument,
 * output, and auxiliary shapes in declaration order; *complete is 0 when
 * the provided shapes underdetermine the graph (the out pointers are
 * then NULL).  All returned storage is thread-local, valid until the
 * next call on this thread. */
int MXTPUSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                          const char** keys, const mx_uint* arg_ind_ptr,
                          const mx_uint* arg_shape_data,
                          mx_uint* in_shape_size,
                          const mx_uint** in_shape_ndim,
                          const mx_uint*** in_shape_data,
                          mx_uint* out_shape_size,
                          const mx_uint** out_shape_ndim,
                          const mx_uint*** out_shape_data,
                          mx_uint* aux_shape_size,
                          const mx_uint** aux_shape_ndim,
                          const mx_uint*** aux_shape_data,
                          int* complete);

/* ------------------------------------------------------------------ */
/* Executor surface — bind NDArrays to a symbol and run forward/backward.
 * Reference analogue: c_api_executor.cc:11-157 (MXExecutorBind/Forward/
 * Backward/Outputs).
 *
 * Bind contract: arg_handles are aligned with MXTPUSymbolListArguments
 * order; aux_handles with MXTPUSymbolListAuxiliaryStates.  grad_handles
 * may be NULL (no gradients) or an array where entry i is NULL or a
 * buffer that MXTPUExecutorBackward fills IN PLACE for argument i.
 * grad_req_types uses the reference OpReqType codes: 0=null 1=write
 * 2=write-inplace 3=add. */
int MXTPUExecutorBind(SymbolHandle sym, int dev_type, int dev_id,
                      mx_uint num_args, NDArrayHandle* arg_handles,
                      NDArrayHandle* grad_handles,
                      const mx_uint* grad_req_types,
                      mx_uint num_aux, NDArrayHandle* aux_handles,
                      ExecutorHandle* out);
int MXTPUExecutorForward(ExecutorHandle handle, int is_train);
/* head_grads may be NULL (scalar-loss convention) or num_heads buffers
 * aligned with the symbol's outputs. */
int MXTPUExecutorBackward(ExecutorHandle handle, mx_uint num_heads,
                          NDArrayHandle* head_grads);
/* *out is a freshly allocated handle array (caller: MXTPUFreeHandleArray
 * on the array, MXTPUNDArrayFree on each handle). */
int MXTPUExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                         NDArrayHandle** out);
/* New static shapes -> a NEW executor handle (reference
 * MXExecutorReshape); the old handle stays valid. */
int MXTPUExecutorReshape(ExecutorHandle handle, mx_uint num_args,
                         const char** keys, const mx_uint* arg_ndims,
                         const mx_uint** arg_shapes, ExecutorHandle* out);
int MXTPUExecutorFree(ExecutorHandle handle);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
